"""The analysis CLI process contract, for every entry form.

``python -m rocket_tpu.analysis`` (rocketlint over paths), ``... shard``
(the SPMD auditor), ``... prec`` (the dtype-flow auditor), ``... sched``
(the roofline/schedule auditor), ``... serve`` (the serving-path
auditor), ``... calib`` (measured-vs-predicted calibration) and
``... mem`` (the HBM liveness auditor), ``... repro`` (the determinism
auditor), ``... fault`` (the crash-consistency auditor) and the
``... all`` umbrella must hold the same machine
contract CI scripts depend on: exit
0 on a clean tree, 1 on findings, 2 on usage errors, and one
``--format json`` output shape. The audit
subcommands share one registry (``__main__.AUDIT_SUBCOMMANDS``), so the
contract rows are parameterized over it. Everything runs as a real
subprocess under ``JAX_PLATFORMS=cpu`` — the audit subcommands provision
their own fake 8-device backend, so no test fixture leaks into the
contract.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "fixtures", "analysis")
BUDGETS = os.path.join(REPO, "tests", "fixtures", "budgets")


def run_cli(*args, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    # The CLI must provision its own virtual devices.
    env.pop("XLA_FLAGS", None)
    return subprocess.run(
        [sys.executable, "-m", "rocket_tpu.analysis", *args],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=timeout,
    )


# -- lint form ---------------------------------------------------------------

def test_lint_exit_zero_on_clean_file():
    proc = run_cli(os.path.join(FIXTURES, "good_tracer_leak.py"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_lint_exit_one_on_findings_with_json_shape():
    proc = run_cli("--format", "json",
                   os.path.join(FIXTURES, "bad_tracer_leak.py"))
    assert proc.returncode == 1
    findings = json.loads(proc.stdout)
    assert findings and set(findings[0]) == {"rule", "path", "line",
                                             "message"}
    assert any(f["rule"] == "RKT101" for f in findings)


def test_lint_exit_two_on_usage_errors():
    assert run_cli().returncode == 2                      # no paths
    assert run_cli("--no-such-flag").returncode == 2      # unknown flag
    assert run_cli("does/not/exist.py").returncode == 2   # bad path


def test_list_rules_includes_all_ten_families():
    proc = run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in ("RKT101", "RKT108", "RKT109", "RKT111", "RKT112",
                    "RKT113", "RKT114", "RKT201",
                    "RKT301", "RKT306", "RKT401", "RKT406", "RKT501",
                    "RKT506", "RKT601", "RKT606", "RKT701", "RKT703",
                    "RKT801", "RKT805", "RKT901", "RKT906",
                    "RKT1001", "RKT1006"):
        assert rule_id in proc.stdout


# -- the shared audit-subcommand registry ------------------------------------

def test_audit_registry_covers_every_subcommand():
    """The registry IS the dispatch table: every audit CLI shares the
    flag set and exit-code handling through it."""
    from rocket_tpu.analysis.__main__ import AUDIT_SUBCOMMANDS

    assert set(AUDIT_SUBCOMMANDS) == {"shard", "prec", "sched", "serve",
                                      "calib", "mem", "repro", "fault"}


@pytest.mark.parametrize("sub", ["shard", "prec", "sched", "serve",
                                 "calib", "mem", "repro", "fault"])
def test_every_audit_subcommand_holds_the_usage_contract(sub):
    assert run_cli(sub, "--target", "nope").returncode == 2
    assert run_cli(sub, "--update-budgets").returncode == 2  # no --budgets
    assert run_cli(sub, "--list-targets").returncode == 0


# -- seeded-bad demos: exact rule sets ---------------------------------------

#: (subcommand, demo target) -> the EXACT finding set the seeded
#: defects produce. Exact, not superset: a demo that starts firing an
#: extra rule has either grown a new defect or broken a rule's
#: precision, and both deserve a red test. One row per demo target in
#: every audit registry — completeness is enforced below.
DEMO_EXPECTED = {
    ("shard", "badrules"): {"RKT301", "RKT304", "RKT305"},
    ("prec", "badprec"): {"RKT401", "RKT402", "RKT403", "RKT404",
                          "RKT405"},
    ("sched", "badsched"): {"RKT501", "RKT502", "RKT503", "RKT505"},
    ("sched", "badoverlap"): {"RKT501", "RKT502", "RKT503"},
    ("sched", "badpallas"): {"RKT504"},
    ("serve", "badserve"): {"RKT601", "RKT602", "RKT603", "RKT604",
                            "RKT605"},
    ("mem", "badmem"): {"RKT801", "RKT802", "RKT804"},
    ("repro", "badrepro"): {"RKT901", "RKT902"},
    ("fault", "badfault"): {"RKT1001", "RKT1002", "RKT1003"},
}


def test_every_demo_target_has_a_pinned_rule_set():
    """Every demo target in every audit registry must carry a
    DEMO_EXPECTED row — a new seeded-bad fixture without a pinned set
    is a true-positive test that silently doesn't exist."""
    from rocket_tpu.analysis.__main__ import AUDIT_SUBCOMMANDS

    demos = set()
    for sub, cli in AUDIT_SUBCOMMANDS.items():
        targets, _run = cli.load()
        for name, target in targets.items():
            if getattr(target, "demo", False):
                demos.add((sub, name))
    assert demos == set(DEMO_EXPECTED)


@pytest.mark.parametrize("sub,target", sorted(DEMO_EXPECTED))
def test_demo_target_fails_with_exactly_the_seeded_rules(sub, target):
    """True positives through the real CLI: each seeded-bad demo must
    exit 1 with exactly its seeded finding families, in the shared JSON
    shape."""
    proc = run_cli(sub, "--target", target, "--format", "json")
    assert proc.returncode == 1, proc.stdout + proc.stderr
    findings = json.loads(proc.stdout)
    assert set(findings[0]) == {"rule", "path", "line", "message"}
    assert {f["rule"] for f in findings} == DEMO_EXPECTED[(sub, target)]


# -- shard form --------------------------------------------------------------

def test_shard_usage_errors_exit_two():
    assert run_cli("shard", "--target", "nope").returncode == 2
    assert run_cli("shard", "--update-budgets").returncode == 2  # no --budgets


def test_shard_list_targets():
    proc = run_cli("shard", "--list-targets")
    assert proc.returncode == 0
    for name in ("tp_2x4", "tp_1x8", "fsdp_1x8", "badrules"):
        assert name in proc.stdout


def test_shard_self_gate_is_clean_and_budgets_hold():
    """THE acceptance gate: the repo's own rule sets on the repo's own
    model, under fake 1x8 / 2x4 meshes, with the committed budget files
    — zero findings, exit 0."""
    proc = run_cli("shard", "--budgets",
                   os.path.join("tests", "fixtures", "budgets"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_shard_self_provisions_platform_without_env():
    """The shard form must provision its own CPU backend and 8 virtual
    devices even when neither JAX_PLATFORMS nor XLA_FLAGS is set (jax is
    imported by the package __init__ before __main__ runs, so the CLI
    routes the platform default through jax.config, not just the env)."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("JAX_PLATFORMS", "XLA_FLAGS")}
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.analysis", "shard",
         "--target", "tp_2x4"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=300,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


# (Seeded-bad true positives for every family run in the DEMO_EXPECTED
# meta-test above.)


# -- prec form ---------------------------------------------------------------

PREC_BUDGETS = os.path.join(REPO, "tests", "fixtures", "budgets", "prec")


def test_prec_usage_errors_exit_two():
    assert run_cli("prec", "--target", "nope").returncode == 2
    assert run_cli("prec", "--update-budgets").returncode == 2  # no --budgets


def test_prec_list_targets():
    proc = run_cli("prec", "--list-targets")
    assert proc.returncode == 0
    for name in ("tp_2x4", "tp_1x8", "fsdp_1x8", "tp_2x4_eval", "badprec"):
        assert name in proc.stdout


def test_prec_self_gate_is_clean_and_budgets_hold():
    """THE acceptance gate: the repo's own bf16 train/eval steps under
    the committed numerics budgets — zero findings, exit 0."""
    proc = run_cli("prec", "--budgets",
                   os.path.join("tests", "fixtures", "budgets", "prec"))
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_prec_budget_regression_fails_and_rebaseline_clears(tmp_path):
    """Diff mode: shrink the committed fp32-bytes fraction (equivalently
    the measured fraction grew) -> RKT406, exit 1; --update-budgets
    re-baselines and the same diff passes."""
    budgets_dir = tmp_path / "prec"
    budgets_dir.mkdir()
    committed = json.load(open(os.path.join(PREC_BUDGETS, "tp_2x4.json")))
    committed["fp32_bytes_fraction"] = committed["fp32_bytes_fraction"] * 0.5
    (budgets_dir / "tp_2x4.json").write_text(json.dumps(committed))

    proc = run_cli("prec", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 1
    assert "RKT406" in proc.stdout
    assert "fp32_bytes_fraction" in proc.stdout

    proc = run_cli("prec", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir), "--update-budgets")
    assert proc.returncode == 0

    proc = run_cli("prec", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_shard_budget_regression_fails_and_rebaseline_clears(tmp_path):
    """Diff mode: shrink the committed collective-bytes record by half
    (equivalently: the measured bytes grew 2x) -> RKT306, exit 1; then
    --update-budgets re-baselines and the same diff passes."""
    budgets_dir = tmp_path / "budgets"
    budgets_dir.mkdir()
    committed = json.load(open(os.path.join(BUDGETS, "tp_2x4.json")))
    committed["collective_bytes_per_step"] = int(
        committed["collective_bytes_per_step"] * 0.5
    )
    (budgets_dir / "tp_2x4.json").write_text(json.dumps(committed))

    proc = run_cli("shard", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 1
    assert "RKT306" in proc.stdout
    assert "collective_bytes_per_step" in proc.stdout

    proc = run_cli("shard", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir), "--update-budgets")
    assert proc.returncode == 0
    rebaselined = json.load(open(budgets_dir / "tp_2x4.json"))
    assert rebaselined["collective_bytes_per_step"] > \
        committed["collective_bytes_per_step"]

    proc = run_cli("shard", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- sched form --------------------------------------------------------------

SCHED_BUDGETS = os.path.join(REPO, "tests", "fixtures", "budgets", "sched")


def test_sched_list_targets():
    proc = run_cli("sched", "--list-targets")
    assert proc.returncode == 0
    for name in ("tp_2x4", "tp_1x8", "fsdp_1x8", "dp_resnet_1x8",
                 "dp_2slice", "tp_flash", "fused_kernels", "badsched",
                 "badoverlap", "badpallas"):
        assert name in proc.stdout


def test_sched_self_gate_is_clean_and_budgets_hold():
    """THE acceptance gate: the repo's own steps roofline-simulated under
    the committed schedule budgets — zero findings, exit 0."""
    proc = run_cli("sched", "--budgets",
                   os.path.join("tests", "fixtures", "budgets", "sched"),
                   timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- calib form --------------------------------------------------------------


def test_calib_list_targets():
    proc = run_cli("calib", "--list-targets")
    assert proc.returncode == 0
    for name in ("gpt2_sentinel", "fsdp_1x8", "serve_decode"):
        assert name in proc.stdout
    # Each row names what it calibrates against.
    assert "priced_for=TPU v5 lite" in proc.stdout
    assert "budget=serve/tiny" in proc.stdout


def test_calib_rules_listed():
    proc = run_cli("--list-rules")
    assert proc.returncode == 0
    for rule_id in ("RKT701", "RKT702", "RKT703"):
        assert rule_id in proc.stdout


# (The calib self-gate + drifted-budget true-positive e2e runs in
# tests/test_prof.py's slow tier and in scripts/check.sh — each run
# captures a live device trace, too heavy to repeat here.)


# -- serve form --------------------------------------------------------------

SERVE_BUDGETS = os.path.join(REPO, "tests", "fixtures", "budgets", "serve")


def test_serve_list_targets():
    proc = run_cli("serve", "--list-targets")
    assert proc.returncode == 0
    for name in ("tiny", "charlm", "gpt2_geom", "badserve"):
        assert name in proc.stdout
    assert "[demo]" in proc.stdout


def test_serve_self_gate_is_clean_and_budgets_hold():
    """THE acceptance gate: the repo's own serve configs — the real
    decode/prefill programs AOT-compiled, the real scheduler driven
    through the admission lattice — with the committed serving budgets:
    zero findings, exit 0."""
    proc = run_cli("serve", "--budgets",
                   os.path.join("tests", "fixtures", "budgets", "serve"),
                   timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_serve_budget_regression_fails_and_rebaseline_clears(tmp_path):
    """Diff mode: shrink the committed predicted ITL by half
    (equivalently: the prediction grew 2x) -> RKT606, exit 1;
    --update-budgets re-baselines and the same diff passes."""
    budgets_dir = tmp_path / "serve"
    budgets_dir.mkdir()
    committed = json.load(open(os.path.join(SERVE_BUDGETS, "tiny.json")))
    committed["predicted_itl_us"] = committed["predicted_itl_us"] * 0.5
    (budgets_dir / "tiny.json").write_text(json.dumps(committed))

    proc = run_cli("serve", "--target", "tiny",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 1
    assert "RKT606" in proc.stdout
    assert "predicted_itl_us" in proc.stdout

    proc = run_cli("serve", "--target", "tiny",
                   "--budgets", str(budgets_dir), "--update-budgets")
    assert proc.returncode == 0

    proc = run_cli("serve", "--target", "tiny",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- mem form ----------------------------------------------------------------

MEM_BUDGETS = os.path.join(REPO, "tests", "fixtures", "budgets", "mem")


def test_mem_list_targets():
    proc = run_cli("mem", "--list-targets")
    assert proc.returncode == 0
    for name in ("tp_2x4", "tp_1x8", "fsdp_1x8", "tp_2x4_eval",
                 "dp_resnet_1x8", "badmem"):
        assert name in proc.stdout
    assert "[demo]" in proc.stdout


@pytest.mark.slow
def test_mem_self_gate_is_clean_and_budgets_hold():
    """THE acceptance gate: the repo's own train/eval steps
    liveness-simulated under the committed peak-HBM budgets — zero
    findings, exit 0. (The same gate runs as a scripts/check.sh stage;
    slow tier here because the sweep AOT-compiles five targets.)"""
    proc = run_cli("mem", "--budgets",
                   os.path.join("tests", "fixtures", "budgets", "mem"),
                   timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_mem_budget_regression_fails_and_rebaseline_clears(tmp_path):
    """Diff mode: shrink the committed predicted peak by half
    (equivalently: the simulated peak grew 2x) -> RKT803, exit 1;
    --update-budgets re-baselines and the same diff passes."""
    budgets_dir = tmp_path / "mem"
    budgets_dir.mkdir()
    committed = json.load(open(os.path.join(MEM_BUDGETS, "fsdp_1x8.json")))
    committed["predicted_peak_bytes"] = int(
        committed["predicted_peak_bytes"] * 0.5
    )
    (budgets_dir / "fsdp_1x8.json").write_text(json.dumps(committed))

    proc = run_cli("mem", "--target", "fsdp_1x8",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 1
    assert "RKT803" in proc.stdout
    assert "predicted_peak_bytes" in proc.stdout

    proc = run_cli("mem", "--target", "fsdp_1x8",
                   "--budgets", str(budgets_dir), "--update-budgets")
    assert proc.returncode == 0

    proc = run_cli("mem", "--target", "fsdp_1x8",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_sched_budget_regression_fails_and_rebaseline_clears(tmp_path):
    """Diff mode: shrink the committed predicted step time by half
    (equivalently: the prediction grew 2x) -> RKT506, exit 1;
    --update-budgets re-baselines and the same diff passes."""
    budgets_dir = tmp_path / "sched"
    budgets_dir.mkdir()
    committed = json.load(
        open(os.path.join(SCHED_BUDGETS, "tp_2x4.json"))
    )
    committed["predicted_step_time_us"] = (
        committed["predicted_step_time_us"] * 0.5
    )
    (budgets_dir / "tp_2x4.json").write_text(json.dumps(committed))

    proc = run_cli("sched", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 1
    assert "RKT506" in proc.stdout
    assert "predicted_step_time_us" in proc.stdout

    proc = run_cli("sched", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir), "--update-budgets")
    assert proc.returncode == 0

    proc = run_cli("sched", "--target", "tp_2x4",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- repro form --------------------------------------------------------------

REPRO_BUDGETS = os.path.join(REPO, "tests", "fixtures", "budgets", "repro")


def test_repro_list_targets():
    proc = run_cli("repro", "--list-targets")
    assert proc.returncode == 0
    for name in ("tp_1x8", "fsdp_1x8", "dp_resnet_1x8", "moe",
                 "charlm_wave", "gpt2_sentinel", "badrepro"):
        assert name in proc.stdout
    assert "[demo]" in proc.stdout
    # Each row names which harness audits it.
    assert "kind=train" in proc.stdout
    assert "kind=serve" in proc.stdout
    assert "kind=exec" in proc.stdout


def test_repro_sentinel_proves_bitwise_replay():
    """RKT905 every CI run: the sentinel step EXECUTES twice from
    identical donated state and must replay bit-for-bit — this is the
    one dynamic leg of the determinism audit, cheap enough to never be
    slow-tiered."""
    proc = run_cli("repro", "--target", "gpt2_sentinel",
                   "--budgets", REPRO_BUDGETS)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_repro_self_gate_is_clean_and_budgets_hold():
    """THE acceptance gate: key discipline, compiled determinism,
    resume-identity and wave-replay proofs over every real target, with
    the committed fingerprint budgets — zero findings, exit 0."""
    proc = run_cli("repro", "--budgets", REPRO_BUDGETS, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_repro_fingerprint_drift_fails_and_rebaseline_clears(tmp_path):
    """Diff mode: tamper with the committed program fingerprint (a
    string identity, not a monotone cost) -> RKT906, exit 1;
    --update-budgets re-baselines and the same diff passes."""
    budgets_dir = tmp_path / "repro"
    budgets_dir.mkdir()
    committed = json.load(
        open(os.path.join(REPRO_BUDGETS, "gpt2_sentinel.json"))
    )
    committed["program_fingerprint"] = "0" * 16
    (budgets_dir / "gpt2_sentinel.json").write_text(json.dumps(committed))

    proc = run_cli("repro", "--target", "gpt2_sentinel",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 1
    assert "RKT906" in proc.stdout
    assert "program_fingerprint" in proc.stdout

    proc = run_cli("repro", "--target", "gpt2_sentinel",
                   "--budgets", str(budgets_dir), "--update-budgets")
    assert proc.returncode == 0

    proc = run_cli("repro", "--target", "gpt2_sentinel",
                   "--budgets", str(budgets_dir))
    assert proc.returncode == 0, proc.stdout + proc.stderr


# -- budgets <-> targets bijection -------------------------------------------

def test_budget_files_match_registered_targets():
    """No stale budget file may name a target that no longer exists (a
    deleted target would otherwise keep gating nothing, silently), and
    demo targets never get budget files. The repro family additionally
    holds an exact bijection: every non-demo target has a committed
    fingerprint baseline."""
    from rocket_tpu.analysis import budgets as budgets_mod
    from rocket_tpu.analysis.__main__ import AUDIT_SUBCOMMANDS

    for sub, cli in AUDIT_SUBCOMMANDS.items():
        targets, _run = cli.load()
        family_dir = os.path.join(
            REPO, getattr(budgets_mod, cli.budgets_dir_attr)
        )
        committed = {
            os.path.splitext(f)[0] for f in os.listdir(family_dir)
            if f.endswith(".json")
        }
        non_demo = {n for n, t in targets.items() if not t.demo}
        stale = committed - non_demo
        assert not stale, f"{sub}: stale/demo budget files {sorted(stale)}"
    from rocket_tpu.analysis.repro_audit import REPRO_TARGETS

    repro_committed = {
        os.path.splitext(f)[0]
        for f in os.listdir(os.path.join(REPO, budgets_mod.REPRO_DIR))
        if f.endswith(".json")
    }
    repro_non_demo = {n for n, t in REPRO_TARGETS.items() if not t.demo}
    assert repro_committed == repro_non_demo


# -- the `all` umbrella ------------------------------------------------------

def test_all_usage_errors_exit_two():
    assert run_cli("all", "--no-such-flag").returncode == 2


@pytest.mark.slow
def test_all_lints_given_paths_with_merged_findings():
    """The umbrella's lint leg (bad fixture, no budgets): findings from
    rocketlint surface through the same JSON shape and exit 1. Slow:
    `all` always sweeps every audit family too, so even the lint-leg
    assertion costs a full eight-family sweep — scripts/check.sh
    exercises the umbrella on every CI run regardless."""
    proc = run_cli("all", os.path.join(FIXTURES, "bad_tracer_leak.py"),
                   "--format", "json", timeout=1200)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    findings = json.loads(proc.stdout)
    assert any(f["rule"] == "RKT101" for f in findings)
    assert set(findings[0]) == {"rule", "path", "line", "message"}


@pytest.mark.slow
def test_all_self_gate_is_clean_with_budgets_and_report(tmp_path):
    """One invocation instead of eight: rocketlint + every audit family
    against the committed budgets — exit 0, and the --json-report
    artifact is written (an empty list when clean)."""
    report = tmp_path / "report.json"
    proc = run_cli("all", "--budgets",
                   os.path.join("tests", "fixtures", "budgets"),
                   "--json-report", str(report), timeout=1800)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert json.loads(report.read_text()) == []
