"""End-to-end training on the virtual 8-device CPU mesh.

The full capsule tree — Dataset / Module(Loss, Optimizer, Scheduler) / Meter /
Metric / Tracker — with the hot path compiled to one jitted step, batch
sharded over the 8-device data axis (real GSPMD collectives on fake devices).
"""

import numpy as np
import optax
import pytest

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.nn.module import Model
from rocket_tpu.utils.metrics import Accuracy


def make_dataset(n=512, dim=8, classes=4, seed=0):
    """Linearly separable gaussian clusters."""
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, dim)) * 3.0
    labels = rng.integers(0, classes, size=n)
    images = centers[labels] + rng.normal(size=(n, dim)) * 0.5
    return [
        {"image": images[i].astype(np.float32), "label": np.int32(labels[i])}
        for i in range(n)
    ]


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def build_tree(runtime, model, data, num_epochs, accum_note=None, batch_size=64):
    train_module = rt.Module(
        model,
        capsules=[
            rt.Loss(cross_entropy),
            rt.Optimizer(optim.adam(), learning_rate=1e-2),
            rt.Scheduler(optim.constant_lr(1e-2)),
        ],
    )
    acc = Accuracy()
    tree = rt.Launcher(
        [
            rt.Looper(
                [rt.Dataset(data, batch_size=batch_size, shuffle=True), train_module],
                tag="train",
            ),
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=batch_size),
                    rt.Module(model),
                    rt.Meter(["logits", "label"], [acc]),
                ],
                tag="val",
                grad_enabled=False,
            ),
        ],
        num_epochs=num_epochs,
        runtime=runtime,
    )
    return tree, acc


def test_training_learns(runtime8):
    model = MLP(in_features=8, num_classes=4, hidden=(32,))
    data = make_dataset()
    tree, acc = build_tree(runtime8, model, data, num_epochs=3)
    tree.launch()
    assert acc.value is not None
    assert acc.value > 0.95, f"accuracy {acc.value}"


def test_loss_decreases(runtime8):
    model = MLP(in_features=8, num_classes=4, hidden=(32,))
    data = make_dataset()
    losses = []

    class LossSpy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.mode == "train" and attrs.looper.state.loss is not None:
                losses.append(float(np.asarray(attrs.looper.state.loss)))

    train_module = rt.Module(
        model, capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)]
    )
    rt.Launcher(
        [
            rt.Looper(
                [rt.Dataset(data, batch_size=64, shuffle=True), train_module, LossSpy()],
                tag="train",
            )
        ],
        num_epochs=2,
        runtime=runtime8,
    ).launch()
    assert len(losses) > 4
    assert losses[-1] < losses[0] * 0.5, f"first {losses[0]}, last {losses[-1]}"


def test_gradient_accumulation_boundary(tmp_path):
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(
        mesh_shape={"data": 8},
        seed=0,
        gradient_accumulation_steps=4,
        project_dir=str(tmp_path),
    )
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    data = make_dataset(n=256)
    sync_flags = []

    class SyncSpy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.mode == "train":
                sync_flags.append(attrs.sync_gradients)

    opt_capsule = rt.Optimizer(optim.adam(), learning_rate=1e-2)
    train_module = rt.Module(model, capsules=[rt.Loss(cross_entropy), opt_capsule])
    rt.Launcher(
        [
            rt.Looper(
                [rt.Dataset(data, batch_size=32), train_module, SyncSpy()],
                tag="train",
            )
        ],
        num_epochs=1,
        runtime=runtime,
    ).launch()
    # 256/32 = 8 micro steps, boundary every 4.
    assert sync_flags == [False, False, False, True] * 2
    assert opt_capsule.iter_idx == 2


def test_gradient_accumulation_spans_epoch_boundary(tmp_path):
    # Odd batches-per-epoch with accum=2: the boundary is derived from the
    # global step, so windows legitimately span epochs — host flags must
    # track the device updates exactly (regression: a per-epoch host counter
    # drifted from the device state).
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(
        mesh_shape={"data": 8},
        seed=0,
        gradient_accumulation_steps=2,
        project_dir=str(tmp_path),
    )
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    data = make_dataset(n=96)  # 3 batches of 32 per epoch
    sync_flags = []
    steps = []

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.mode == "train":
                sync_flags.append(attrs.sync_gradients)
                steps.append(int(np.asarray(
                    attrs.step_metrics and attrs.step_metrics.loss is not None
                )))

    train_module = rt.Module(
        model, capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)]
    )
    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=32), train_module, Spy()], tag="train")],
        num_epochs=2,
        runtime=runtime,
    ).launch()
    # global steps 1..6, boundary at even steps — spanning the epoch break.
    assert sync_flags == [False, True, False, True, False, True]


def test_scheduler_decays_lr(runtime8):
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    data = make_dataset(n=256)
    lrs = []

    class LrSpy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.mode == "train" and attrs.looper.state.lr is not None:
                lrs.append(float(np.asarray(attrs.looper.state.lr)))

    train_module = rt.Module(
        model,
        capsules=[
            rt.Loss(cross_entropy),
            rt.Optimizer(optim.sgd()),
            rt.Scheduler(optim.step_lr(0.1, step_size=2, gamma=0.5)),
        ],
    )
    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=64), train_module, LrSpy()], tag="train")],
        num_epochs=1,
        runtime=runtime8,
    ).launch()
    assert lrs[0] == pytest.approx(0.1)
    assert lrs[-1] < 0.1


def test_shared_model_prepared_once(runtime8):
    # One model in train and eval capsules -> one prepared record, identical
    # state object (prepare-once semantics, module.py:29-43).
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    data = make_dataset(n=128)
    tree, acc = build_tree(runtime8, model, data, num_epochs=1)
    tree.setup(rt.Attributes())
    assert len(runtime8.models) == 1


def test_batch_is_sharded_over_mesh(runtime8):
    placed = {}

    class ShardSpy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.batch is not None and "image" in attrs.batch:
                placed["sharding"] = attrs.batch["image"].sharding
                attrs.looper.terminate = True

    data = make_dataset(n=64)
    rt.Launcher(
        # fuse_gather=False: this spy consumes attrs.batch directly (no
        # Module to materialize a gather marker inside its step).
        [rt.Looper(
            [rt.Dataset(data, batch_size=64, fuse_gather=False), ShardSpy()],
            tag="train",
        )],
        num_epochs=1,
        runtime=runtime8,
    ).launch()
    sharding = placed["sharding"]
    # 8-way sharded on the leading (batch) axis.
    assert sharding.num_devices == 8
    shard_shape = sharding.shard_shape((64, 8))
    assert shard_shape == (8, 8)


def test_fused_gather_marker_trains_and_matches_unfused(runtime8, tmp_path):
    """Device-resident Datasets yield gather markers materialized INSIDE
    the compiled step (one dispatch per step); losses must match the
    unfused per-batch-gather path exactly (same cache, same permutation)."""
    import numpy as np

    def run(fuse):
        runtime = rt.Runtime(
            mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path)
        )
        model = MLP(in_features=8, num_classes=4, hidden=(16,))
        data = make_dataset(n=128)
        losses = []

        class Spy(rt.Capsule):
            def __init__(self):
                super().__init__(priority=500)

            def launch(self, attrs=None):
                # The marker never leaks to downstream capsules' batch view
                # in eval; in train attrs.batch stays whatever Dataset set.
                losses.append(float(np.asarray(attrs.step_metrics.loss)))

        module = rt.Module(
            model,
            capsules=[
                rt.Loss(cross_entropy),
                rt.Optimizer(optim.sgd(), learning_rate=0.1),
            ],
        )
        rt.Launcher(
            [rt.Looper(
                [rt.Dataset(data, batch_size=32, fuse_gather=fuse,
                            shuffle=True), module, Spy()],
                tag="train", progress=False,
            )],
            num_epochs=2,
            runtime=runtime,
        ).launch()
        return losses

    fused, unfused = run(True), run(False)
    assert len(fused) == len(unfused) == 8
    np.testing.assert_allclose(fused, unfused, rtol=1e-5)


@pytest.mark.parametrize("accum", [1, 2])
def test_gradient_clipping_bounds_update(tmp_path, accum):
    """Optimizer(clip_norm=c) with plain SGD(lr) bounds every update's
    global norm by lr * c; the pre-clip grad_norm metric reports what the
    clip acts on (mean grads at the boundary under accumulation)."""
    import jax
    import jax.numpy as jnp

    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        gradient_accumulation_steps=accum,
    )
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    data = make_dataset(n=64)
    module = rt.Module(
        model,
        capsules=[
            rt.Loss(cross_entropy),
            # lr huge so an unclipped first step would move params by >> 1.
            rt.Optimizer(optim.sgd(), learning_rate=1.0, clip_norm=1e-3),
        ],
    )
    snapshots = []
    grad_norms = []

    class ParamSpy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)  # after the Module's step

        def launch(self, attrs=None):
            if attrs.mode == "train":
                # Host copies: the step donates its state buffers, so device
                # references would be deleted by the next step.
                snapshots.append(
                    jax.tree.map(lambda x: np.asarray(x), module.state["params"])
                )
                grad_norms.append(float(np.asarray(attrs.step_metrics.grad_norm)))

    launcher = rt.Launcher(
        [
            rt.Looper(
                [rt.Dataset(data, batch_size=64), module, ParamSpy()],
                tag="train", progress=False,
            )
        ],
        num_epochs=2,
        runtime=runtime,
    )
    launcher.launch()
    assert len(snapshots) == 2
    delta = jax.tree.map(lambda a, b: a - b, snapshots[1], snapshots[0])
    norm = float(
        jnp.sqrt(
            sum(jnp.sum(l.astype(jnp.float32) ** 2) for l in jax.tree.leaves(delta))
        )
    )
    if accum == 1:
        assert 0.0 < norm <= 1e-3 * 1.01, norm
    else:
        # Two epochs x one batch = one window: snapshot[0] is off-boundary
        # (no update yet), snapshot[1] is right after the clipped update.
        assert 0.0 < norm <= 1e-3 * 1.01, norm
    # clip_norm also surfaces the PRE-clip grad norm of what the clip acts
    # on; off-boundary micro-steps report 0.
    assert len(grad_norms) == 2, grad_norms
    assert max(grad_norms) > 1e-3, grad_norms
    if accum == 2:
        assert grad_norms[0] == 0.0, grad_norms


@pytest.mark.parametrize("accum", [1, 2])
def test_ema_tracks_params_and_eval_uses_it(tmp_path, accum):
    """state["ema_params"] follows the EMA recurrence at sync boundaries,
    and an eval Module with use_ema forwards with the shadow params."""
    import jax
    import jax.numpy as jnp

    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        gradient_accumulation_steps=accum,
    )
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    data = make_dataset(n=128 * accum)
    decay = 0.5  # aggressive so the shadow visibly lags
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.sgd(), learning_rate=0.5)],
        ema_decay=decay,
    )
    snaps = []

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.mode == "train" and attrs.sync_gradients:
                snaps.append({
                    "params": jax.tree.map(lambda x: np.asarray(x), module.state["params"]),
                    "ema": jax.tree.map(lambda x: np.asarray(x), module.state["ema_params"]),
                })

    eval_batches = []

    class EvalSpy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.mode == "eval" and attrs.batch is not None:
                eval_batches.append(
                    np.asarray(attrs.batch["logits"], np.float32)
                )

    eval_module = rt.Module(model, use_ema=True)
    launcher = rt.Launcher(
        [
            rt.Looper(
                [rt.Dataset(data, batch_size=64), module, Spy()],
                tag="train", progress=False,
            ),
            rt.Looper(
                [rt.Dataset(data[:64], batch_size=64), eval_module, EvalSpy()],
                tag="val", grad_enabled=False, progress=False,
            ),
        ],
        num_epochs=1,
        runtime=runtime,
    )
    launcher.launch()
    assert len(snaps) == 2  # two optimizer boundaries either way
    # Boundary 2 recurrence: ema2 = ema1 + (1-d)(params2 - ema1).
    expect = jax.tree.map(
        lambda e1, p2: e1 + (1 - decay) * (p2 - e1),
        snaps[0]["ema"], snaps[1]["params"],
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-6),
        expect, snaps[1]["ema"],
    )
    # The shadow genuinely lags the raw params.
    gap = max(
        float(np.max(np.abs(e - p)))
        for e, p in zip(
            jax.tree.leaves(snaps[1]["ema"]), jax.tree.leaves(snaps[1]["params"])
        )
    )
    assert gap > 1e-4, gap
    # The eval forward genuinely used the EMA params: its logits match a
    # manual forward with the final shadow, not with the raw params.
    first_image = data[0]["image"]
    eval_logits = eval_batches[0][0]

    state_template = model.init(jax.random.key(0))["state"]

    def forward_with(params):
        out, _ = model.apply(
            {"params": jax.tree.map(jnp.asarray, params), "state": state_template},
            {"image": jnp.asarray(first_image)[None]},
            mode="eval",
        )
        return np.asarray(out["logits"][0], np.float32)

    np.testing.assert_allclose(
        eval_logits, forward_with(snaps[-1]["ema"]), rtol=1e-4, atol=1e-5
    )
    raw = forward_with(snaps[-1]["params"])
    assert np.max(np.abs(eval_logits - raw)) > 1e-4  # and NOT the raw params


def test_use_ema_without_train_ema_errors(tmp_path):
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path))
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    eval_module = rt.Module(model, use_ema=True, runtime=runtime)
    eval_module.setup()  # order-insensitive: the check happens at launch
    attrs = rt.Attributes()
    attrs.mode = "eval"
    attrs.batch = {"image": np.zeros((8, 8), np.float32)}
    with pytest.raises(RuntimeError, match="use_ema"):
        eval_module.launch(attrs)


def test_ema_decay_requires_optimizer(tmp_path):
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path))
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    module = rt.Module(model, ema_decay=0.99, runtime=runtime)
    with pytest.raises(RuntimeError, match="ema_decay requires"):
        module.setup()


def test_kitchen_sink_train_save_resume(tmp_path):
    """Every training feature at once — EMA + clip_norm + grad_norm metric
    + on-device augmentation + gradient accumulation + scheduler +
    checkpoint save — then a resume that restores params, EMA shadow and
    counters, and actually trains on past the restored step."""
    import jax

    from rocket_tpu.data.augment import image_augment
    from rocket_tpu.runtime.context import Runtime

    rng = np.random.default_rng(0)
    data = [
        {"image": rng.normal(size=(8, 8, 1)).astype(np.float32),
         "label": np.int32(rng.integers(0, 4))}
        for _ in range(128)
    ]

    def objective(b):
        return optax.softmax_cross_entropy_with_integer_labels(
            b["logits"], b["label"]
        ).mean()

    def build_sink(runtime, resume_from=None, extra=()):
        # MLP's trunk starts with Flatten, so NHWC images feed it directly.
        model = MLP(in_features=64, num_classes=4, hidden=(16,))
        module = rt.Module(
            model,
            capsules=[
                rt.Loss(objective),
                rt.Optimizer(optim.adamw(), learning_rate=1e-2, clip_norm=1.0),
                rt.Scheduler(optim.warmup_cosine_lr(1e-2, 2, 16)),
            ],
            ema_decay=0.9,
            batch_transform=image_augment(crop_padding=1, flip=True),
        )
        launcher = rt.Launcher(
            [
                rt.Looper(
                    [
                        rt.Dataset(data, batch_size=32, shuffle=True),
                        module,
                        rt.Checkpointer(output_dir=str(tmp_path / "ck"),
                                        save_every=2, resume_from=resume_from),
                        *extra,
                    ],
                    tag="train", progress=False,
                )
            ],
            num_epochs=1, statefull=True, runtime=runtime,
        )
        return launcher, module

    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        gradient_accumulation_steps=2,
    )
    snaps = {}
    module_ref = []

    class Snap(rt.Capsule):
        def __init__(self):
            super().__init__(priority=20)  # after the checkpointer's save

        def launch(self, attrs=None):
            state = module_ref[0].state
            # Snapshot the state the mid-epoch step-2 checkpoint captured.
            if int(np.asarray(state["step"])) == 2:
                snaps["params"] = jax.tree.map(lambda x: np.asarray(x), state["params"])
                snaps["ema"] = jax.tree.map(lambda x: np.asarray(x), state["ema_params"])

    launcher, module = build_sink(runtime, extra=(Snap(),))
    module_ref.append(module)
    launcher.launch()
    assert "params" in snaps

    # Resume from the mid-epoch step-2 checkpoint: params AND the EMA
    # shadow restore exactly (seed=7 ensures a fresh init could not match).
    runtime2 = Runtime(
        mesh_shape={"data": 8}, seed=7, project_dir=str(tmp_path),
        gradient_accumulation_steps=2,
    )
    launcher2, module2 = build_sink(
        runtime2, resume_from=str(tmp_path / "ck" / "2"))
    launcher2.setup(rt.Attributes())
    assert int(np.asarray(module2.state["step"])) == 2
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
        snaps["params"], module2.state["params"],
    )
    jax.tree.map(
        lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
        snaps["ema"], module2.state["ema_params"],
    )

    # A THIRD tree does the full resumed run end-to-end: fast-forwards the
    # mid-epoch data stream, trains the remaining steps, tears down clean.
    runtime3 = Runtime(
        mesh_shape={"data": 8}, seed=7, project_dir=str(tmp_path),
        gradient_accumulation_steps=2,
    )
    final = {}
    module3_ref = []

    class Final(rt.Capsule):
        def __init__(self):
            super().__init__(priority=10)

        def launch(self, attrs=None):
            final["step"] = int(np.asarray(module3_ref[0].state["step"]))

    launcher3, module3 = build_sink(
        runtime3, resume_from=str(tmp_path / "ck" / "2"), extra=(Final(),))
    module3_ref.append(module3)
    launcher3.launch()
    assert final["step"] == 4, final  # trained past the restored step


class _UntraceableInitModel(Model):
    """init() concretizes the traced key -> trace-time failure under jit."""

    def init(self, key):
        import jax

        # np.asarray on a tracer raises TracerArrayConversionError under
        # jit; eagerly it works fine.
        seed = int(np.asarray(jax.random.key_data(key)).sum()) % (2**31)
        w = np.random.default_rng(seed).normal(size=(8, 4)).astype(np.float32)
        return {"params": {"w": w}}

    def apply(self, variables, batch, *, mode="train", rng=None):
        out = dict(batch)
        out["logits"] = batch["image"] @ variables["params"]["w"]
        return out, {}


class _BrokenInitModel(_UntraceableInitModel):
    """init() raises a genuine user error — must propagate, not fall back
    to a second eager execution (round-4 advisor)."""

    def init(self, key):
        raise ValueError("broken init: deliberate")


def test_untraceable_init_falls_back_to_eager(runtime8, caplog):
    import logging

    model = _UntraceableInitModel()
    module = rt.Module(model, runtime=runtime8)
    with caplog.at_level(logging.WARNING):
        module.setup()
    assert module.state["params"]["w"].shape == (8, 4)
    # The fallback is loud: a warning names the trace failure.
    assert any("falling back to eager init" in r.message for r in caplog.records)
    module.destroy()


def test_broken_init_propagates_once(runtime8):
    model = _BrokenInitModel()
    module = rt.Module(model, runtime=runtime8)
    with pytest.raises(ValueError, match="broken init"):
        module.setup()
