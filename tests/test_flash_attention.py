"""Flash attention kernel vs the XLA reference path.

Runs in pallas interpret mode on the virtual CPU mesh (same kernel code the
TPU compiles — see ops/flash_attention.py).
"""

import jax
import jax.numpy as jnp
import pytest

from rocket_tpu.nn.attention import (
    MultiHeadAttention,
    dot_product_attention,
    resolve_impl,
)
from rocket_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, h=4, t=256, d=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, (b, h, t, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_forward(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_grads(causal):
    q, k, v = _qkv(b=1, h=2, t=256, d=32)

    def loss(attn):
        return lambda q, k, v: (attn(q, k, v) ** 2).sum()

    ref_fn = loss(lambda q, k, v: dot_product_attention(q, k, v, causal=causal))
    fl_fn = loss(
        lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=128, block_k=128
        )
    )
    g_ref = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(fl_fn, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_flash_non_square_blocks_non_causal():
    q, k, v = _qkv(t=512)
    ref = dot_product_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, block_q=256, block_k=128)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5


def test_flash_rejects_ragged_seq():
    q, k, v = _qkv(t=200)
    with pytest.raises(ValueError, match="supported block size"):
        flash_attention(q, k, v, block_q=128, block_k=128)


def test_mha_flash_impl_matches_xla():
    layer_x = MultiHeadAttention(64, 4, impl="xla")
    layer_f = MultiHeadAttention(64, 4, impl="flash")
    params = layer_x.init(jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (2, 256, 64), jnp.float32)
    out_x, _ = layer_x.apply(params, x, mode="eval")
    out_f, _ = layer_f.apply(params, x, mode="eval")
    assert jnp.max(jnp.abs(out_x - out_f)) < 1e-5


def test_resolve_impl_auto_on_cpu_is_xla():
    # The test mesh is CPU: auto must avoid interpreted pallas.
    assert resolve_impl("auto", 1024, 64) == "xla"
    assert resolve_impl("flash", 1024, 64) == "flash"
