"""Flash attention kernel vs the XLA reference path.

Runs in pallas interpret mode on the virtual CPU mesh (same kernel code the
TPU compiles — see ops/flash_attention.py).
"""

import jax
import jax.numpy as jnp
import pytest

from rocket_tpu.nn.attention import (
    MultiHeadAttention,
    dot_product_attention,
    resolve_impl,
)
from rocket_tpu.ops.flash_attention import flash_attention


def _qkv(b=2, h=4, t=256, d=64, dtype=jnp.float32):
    ks = jax.random.split(jax.random.key(0), 3)
    return tuple(jax.random.normal(k, (b, h, t, d), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_forward(causal):
    q, k, v = _qkv()
    ref = dot_product_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=128, block_k=128)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5


@pytest.mark.parametrize("causal", [True, False])
def test_flash_matches_xla_grads(causal):
    q, k, v = _qkv(b=1, h=2, t=256, d=32)

    def loss(attn):
        return lambda q, k, v: (attn(q, k, v) ** 2).sum()

    ref_fn = loss(lambda q, k, v: dot_product_attention(q, k, v, causal=causal))
    fl_fn = loss(
        lambda q, k, v: flash_attention(
            q, k, v, causal=causal, block_q=128, block_k=128
        )
    )
    g_ref = jax.grad(ref_fn, argnums=(0, 1, 2))(q, k, v)
    g_fl = jax.grad(fl_fn, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ref, g_fl):
        assert jnp.max(jnp.abs(a - b)) < 1e-4


def test_flash_non_square_blocks_non_causal():
    q, k, v = _qkv(t=512)
    ref = dot_product_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, block_q=256, block_k=128)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5


def test_flash_rejects_ragged_seq():
    q, k, v = _qkv(t=200)
    with pytest.raises(ValueError, match="supported block size"):
        flash_attention(q, k, v, block_q=128, block_k=128)


def test_mha_flash_impl_matches_xla():
    layer_x = MultiHeadAttention(64, 4, impl="xla")
    layer_f = MultiHeadAttention(64, 4, impl="flash")
    params = layer_x.init(jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (2, 256, 64), jnp.float32)
    out_x, _ = layer_x.apply(params, x, mode="eval")
    out_f, _ = layer_f.apply(params, x, mode="eval")
    assert jnp.max(jnp.abs(out_x - out_f)) < 1e-5


def test_resolve_impl_auto_on_cpu_is_xla():
    # The test mesh is CPU: auto must avoid interpreted pallas.
    assert resolve_impl("auto", 1024, 64) == "xla"
    assert resolve_impl("flash", 1024, 64) == "flash"


# -- multi-device shard_map seam (round-3 verdict item #1) ------------------


def _sharded_case(mesh_shape, qkv_spec, b=8):
    """Build a mesh, sharded stacked qkv, and the flash/xla pair."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import numpy as np

    axis_names = tuple(mesh_shape.keys())
    shape = tuple(mesh_shape.values())
    mesh = Mesh(np.asarray(jax.devices()[: np.prod(shape)]).reshape(shape), axis_names)
    ks = jax.random.split(jax.random.key(0), 3)
    qkv = jnp.stack(
        [jax.random.normal(k, (b, 4, 256, 32), jnp.float32) for k in ks]
    )
    qkv = jax.device_put(qkv, NamedSharding(mesh, P(*qkv_spec)))
    return mesh, qkv


@pytest.mark.parametrize(
    "mesh_shape,qkv_spec",
    [
        ({"data": 8}, (None, "data", None, None, None)),          # dp
        ({"data": 4, "model": 2}, (None, "data", "model", None, None)),  # dp x tp
    ],
)
def test_flash_sharded_matches_xla(mesh_shape, qkv_spec):
    from rocket_tpu.ops.flash_attention import flash_attention_qkv_sharded

    mesh, qkv = _sharded_case(mesh_shape, qkv_spec)
    ref = dot_product_attention(qkv[0], qkv[1], qkv[2], causal=True)

    @jax.jit
    def run(qkv):
        return flash_attention_qkv_sharded(
            qkv, causal=True, mesh=mesh, block_q=128, block_k=128
        )

    out = run(qkv)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5

    # Gradients flow through the seam (custom VJP under shard_map).
    @jax.jit
    def loss(qkv):
        return (
            flash_attention_qkv_sharded(
                qkv, causal=True, mesh=mesh, block_q=128, block_k=128
            )
            ** 2
        ).sum()

    def ref_loss(qkv):
        return (dot_product_attention(qkv[0], qkv[1], qkv[2], causal=True) ** 2).sum()

    g = jax.grad(loss)(qkv)
    g_ref = jax.grad(ref_loss)(qkv)
    assert jnp.max(jnp.abs(g - g_ref)) < 1e-4


def test_flash_sharded_drops_nondividing_axes():
    # B=3 doesn't divide the 8-way data axis; H=4 doesn't divide a 0-size
    # 'model': the seam must degrade to a plain call, not error.
    from jax.sharding import Mesh
    import numpy as np

    from rocket_tpu.ops.flash_attention import flash_attention_qkv_sharded

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    ks = jax.random.split(jax.random.key(0), 3)
    qkv = jnp.stack(
        [jax.random.normal(k, (3, 2, 128, 16), jnp.float32) for k in ks]
    )
    out = flash_attention_qkv_sharded(
        qkv, causal=True, mesh=mesh, block_q=128, block_k=128
    )
    ref = dot_product_attention(qkv[0], qkv[1], qkv[2], causal=True)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5


def test_mha_flash_on_multidevice_mesh(tmp_path):
    """The LAYER routes through the seam on a dp x tp Runtime mesh and
    matches the xla path — the round-2 hard fallback (device_count > 1 ->
    xla) is gone."""
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(
        mesh_shape={"data": 4, "model": 2}, seed=0, project_dir=str(tmp_path)
    )
    layer_x = MultiHeadAttention(64, 4, impl="xla")
    layer_f = MultiHeadAttention(64, 4, impl="flash")
    params = layer_x.init(jax.random.key(1))
    x = jax.device_put(
        jax.random.normal(jax.random.key(2), (8, 256, 64), jnp.float32),
        runtime.batch_sharding,
    )
    out_x, _ = jax.jit(
        lambda p, x: layer_x.apply(p, x, mode="eval")
    )(params, x)
    out_f, _ = jax.jit(
        lambda p, x: layer_f.apply(p, x, mode="eval")
    )(params, x)
    assert layer_f._flash_mesh is runtime.mesh  # seam engaged, mesh pinned
    assert jnp.max(jnp.abs(out_x - out_f)) < 1e-5


def test_in_manual_axes_detection():
    from jax.sharding import Mesh, PartitionSpec as P
    import numpy as np

    from rocket_tpu.ops.flash_attention import in_manual_axes

    assert not in_manual_axes(("data", "model"))

    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map

    mesh = Mesh(np.asarray(jax.devices()), ("data",))
    seen = []

    def body(x):
        seen.append(in_manual_axes(("data",)))
        return x

    jax.jit(
        shard_map(body, mesh=mesh, in_specs=(P("data"),), out_specs=P("data"))
    )(jnp.zeros((8,)))
    assert seen == [True]
