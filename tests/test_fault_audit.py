"""Crash-consistency & failure-path auditor (ISSUE 18, RKT10xx).

Covers the three audit legs (crash-prefix replay, supervisor model
check + conformance, signal-handler scan), the pure rule functions on
synthetic facts, the pure ``decide`` transition function directly, the
badfault seeded-bad demo's exact rule set, and the multi-host
skewed-drain torn-layout story (ranks draining at different steps must
fail ``is_complete_checkpoint`` and resume must fall back to the last
complete periodic save).
"""

import json
import os
import textwrap

import numpy as np

from rocket_tpu.analysis.fault_audit import (
    EVENT_ALPHABET,
    FAULT_TARGETS,
    TERMINAL_OUTCOMES,
    RecordingFS,
    _bad_decide,
    _badfault_journal,
    audit_checkpoint_protocol,
    audit_signal_handlers,
    conformance_check,
    model_check,
    replay_crash_prefixes,
    run_fault_target,
    scan_signal_handlers,
)
from rocket_tpu.analysis.rules.fault_rules import (
    FAULT_RULES,
    check_atomic_commit,
    check_crash_prefixes,
)
from rocket_tpu.resilience.supervisor import (
    GenEvent,
    LoopState,
    RestartPolicy,
    decide,
    is_complete_checkpoint,
    newest_complete_step,
)
from rocket_tpu.runtime import checkpoint_io


def rules_in(findings):
    return sorted({f.rule for f in findings})


# -- atomic_write effect ordering -------------------------------------------


def test_atomic_write_orders_write_fsync_replace(tmp_path):
    rec = RecordingFS(str(tmp_path))
    dest = str(tmp_path / "state.json")
    with checkpoint_io.use_fs(rec):
        checkpoint_io.atomic_write(dest, b'{"ok": 1}')
    ops = [e[0] for e in rec.journal]
    assert ops == ["makedirs", "mktemp", "write", "fsync", "replace"]
    # the fsync targets the temp file the rename then commits
    assert rec.journal[3][1] == rec.journal[2][1] == rec.journal[4][1]
    assert rec.journal[4][2] == "state.json"
    with open(dest, "rb") as f:
        assert f.read() == b'{"ok": 1}'
    # the shim performed the real effects too, and check_atomic_commit
    # has nothing to say about a correct sequence
    assert check_atomic_commit(rec.journal) == []


# -- crash-prefix enumeration over the real save paths ----------------------


def test_checkpoint_protocol_audit_clean_with_total_coverage():
    report = audit_checkpoint_protocol()
    assert report.clean, [f.render() for f in report.findings]
    record = report.record
    # coverage is counted, not assumed: one prefix per journaled effect
    # plus the empty prefix, for each of the three save paths
    expected = sum(
        record[f"effects_{name}"] + 1
        for name in ("save", "save_drain", "save_emergency")
    )
    assert record["crash_points"] == expected
    assert record["effects_save"] > 0
    assert record["effects_save_drain"] > 0
    assert record["effects_save_emergency"] > 0
    assert "coverage_fingerprint" in record


def test_marker_first_journal_yields_accepted_torn_state(tmp_path):
    journal = _badfault_journal(str(tmp_path / "bad"))
    verdicts = replay_crash_prefixes(
        journal, str(tmp_path / "replay"), seed_dir=None)
    assert len(verdicts) == len(journal) + 1
    torn = [v for v in verdicts
            if v["complete"] and not v["consistent"] and not v["final"]]
    assert torn, verdicts  # the marker-first order IS the disease
    assert "RKT1001" in rules_in(check_crash_prefixes(verdicts))
    assert "RKT1002" in rules_in(check_atomic_commit(journal))


# -- the journal rules on synthetic effect sequences ------------------------


def test_rename_without_fsync_fires_rkt1002():
    journal = [
        ("mktemp", "2/.wip1.tmp"),
        ("write", "2/.wip1.tmp"),
        ("replace", "2/.wip1.tmp", "2/index.json"),
    ]
    findings = check_atomic_commit(journal)
    assert rules_in(findings) == ["RKT1002"]
    assert "fsync" in findings[0].message


def test_write_after_marker_fires_except_drain_sidecar():
    base = [
        ("mktemp", "2/.wip1.tmp"),
        ("write", "2/.wip1.tmp"),
        ("fsync", "2/.wip1.tmp"),
        ("replace", "2/.wip1.tmp", "2/rng.json"),
    ]
    assert check_atomic_commit(base) == []
    bad = base + [("write", "2/model_0/index.json")]
    assert rules_in(check_atomic_commit(bad)) == ["RKT1002"]
    # the drain.json sidecar is the documented post-marker exemption,
    # both as a plain write and as a temp-file commit
    sidecar = base + [
        ("mktemp", "2/.wip2.tmp"),
        ("write", "2/.wip2.tmp"),
        ("fsync", "2/.wip2.tmp"),
        ("replace", "2/.wip2.tmp", "2/drain.json"),
    ]
    assert check_atomic_commit(sidecar) == []


def test_check_crash_prefixes_on_synthetic_verdicts():
    clean = [
        {"k": 0, "complete": False, "consistent": True,
         "fallback_ok": True, "fallback_step": 1, "final": False},
        {"k": 1, "complete": True, "consistent": True,
         "fallback_ok": True, "fallback_step": 2, "final": True},
    ]
    assert check_crash_prefixes(clean) == []
    torn = [{"k": 3, "complete": True, "consistent": False,
             "fallback_ok": True, "fallback_step": 2, "final": False}]
    assert rules_in(check_crash_prefixes(torn)) == ["RKT1001"]
    lost = [{"k": 2, "complete": False, "consistent": True,
             "fallback_ok": False, "fallback_step": None, "final": False}]
    assert rules_in(check_crash_prefixes(lost)) == ["RKT1001"]
    rejected_final = [{"k": 9, "complete": False, "consistent": True,
                       "fallback_ok": True, "fallback_step": 1,
                       "final": True}]
    assert rules_in(check_crash_prefixes(rejected_final)) == ["RKT1001"]


# -- the pure transition function -------------------------------------------


CRASH = GenEvent("crashed")


def test_decide_degrades_to_floor_then_crash_loops():
    policy = RestartPolicy(max_restarts=16, crash_loop_threshold=3,
                           degrade_after=2, min_procs=1)
    state = LoopState(nproc=3)
    nprocs = []
    outcome = None
    for _ in range(12):
        d = decide(state, policy, CRASH)
        nprocs.append(d.state.nproc)
        if d.stop:
            outcome = d.outcome
            break
        state = d.state
    # 3 -> degrade at the 2nd failure -> 2 -> degrade -> 1 (the floor),
    # then the crash-loop detector is the only way out
    assert nprocs == [3, 2, 2, 1, 1, 1, 1]
    assert outcome == "crash_loop"
    assert min(nprocs) >= policy.min_procs


def test_decide_drained_certification_requires_checkpoint():
    state = LoopState(nproc=2)
    policy = RestartPolicy()
    no_ckpt = decide(state, policy,
                     GenEvent("drained", complete_ckpt=False, probe=True))
    assert no_ckpt.stop and no_ckpt.outcome == "drain_failed"
    assert not no_ckpt.rc_zero
    with_ckpt = decide(state, policy,
                       GenEvent("drained", complete_ckpt=True, probe=True))
    assert with_ckpt.outcome == "drained" and with_ckpt.rc_zero
    # without a probe there is nothing to check against
    no_probe = decide(state, policy, GenEvent("drained", probe=False))
    assert no_probe.outcome == "drained" and no_probe.rc_zero


def test_decide_coord_error_counts_toward_neither_counter():
    policy = RestartPolicy()
    state = LoopState(nproc=2, consecutive_failures=1, failures_at_nproc=1)
    d = decide(state, policy, GenEvent("crashed", coord_error=True))
    assert not d.stop
    assert d.state.consecutive_failures == 1
    assert d.state.failures_at_nproc == 1


def test_decide_restart_budget_is_a_hard_ceiling():
    policy = RestartPolicy(max_restarts=2, crash_loop_threshold=99,
                           degrade_after=99)
    state = LoopState(nproc=2)
    for expected_restarts in (1, 2):
        d = decide(state, policy, CRASH)
        assert not d.stop
        assert d.state.restarts == expected_restarts
        state = d.state
    d = decide(state, policy, CRASH)
    assert d.stop and d.outcome == "restart_budget_exhausted"


# -- model check + conformance ----------------------------------------------


def test_model_check_clean_and_reaches_every_terminal():
    facts = model_check()
    assert facts["violations"] == []
    assert facts["livelocks"] == []
    assert set(facts["terminals"]) == set(TERMINAL_OUTCOMES)
    assert facts["states_explored"] > 0
    assert facts["transitions_checked"] == (
        facts["states_explored"] * len(EVENT_ALPHABET)
    )
    assert facts["sequences_at_depth"] == len(EVENT_ALPHABET) ** 6


def test_model_check_catches_drained_without_checkpoint():
    facts = model_check(decide_fn=_bad_decide)
    assert any("drained" in v for v in facts["violations"])


def test_conformance_live_loop_matches_transition_function(tmp_path):
    result = conformance_check(str(tmp_path))
    assert result["violations"] == [], result["violations"]
    assert result["runs"] == 4 + 16 + 64  # every rc sequence, len 1..3


# -- signal-handler scan -----------------------------------------------------


def test_repo_signal_handlers_are_flag_set_only():
    report = audit_signal_handlers()
    assert report.clean, [f.render() for f in report.findings]
    assert report.record["handlers_checked"] >= 2  # SIGTERM + SIGINT


def test_signal_scan_fires_on_logging_handler(tmp_path):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad_handlers.py").write_text(textwrap.dedent("""
        import logging
        import signal

        logger = logging.getLogger(__name__)


        def handler(signum, frame):
            logger.warning("caught %s", signum)
            print("shutting down")


        def install():
            signal.signal(signal.SIGTERM, handler)
    """))
    (pkg / "good_handlers.py").write_text(textwrap.dedent("""
        import signal


        class Drain:
            def __init__(self):
                self.requested = False

            def request(self, reason):
                self.requested = True


        def install(drain):
            def handler(signum, frame):
                drain.request("signal")
            signal.signal(signal.SIGTERM, handler)
    """))
    files, handlers, violations = scan_signal_handlers(str(pkg))
    assert files == 2 and handlers == 2
    calls = sorted(v[3] for v in violations)
    assert calls == ["logger.warning", "print"]
    assert all(v[0].endswith("bad_handlers.py") for v in violations)


# -- the seeded-bad demo: exact rule set ------------------------------------


def test_badfault_reports_exactly_the_seeded_rules():
    report = run_fault_target(FAULT_TARGETS["badfault"])
    assert rules_in(report.findings) == ["RKT1001", "RKT1002", "RKT1003"]


def test_fault_family_registered():
    from rocket_tpu.analysis.__main__ import AUDIT_SUBCOMMANDS
    from rocket_tpu.analysis import budgets as budgets_mod

    cli = AUDIT_SUBCOMMANDS["fault"]
    assert cli.budget_rule == "RKT1006"
    assert getattr(budgets_mod, cli.gated_keys_attr) == (
        "crash_points", "states_explored", "handlers_checked",
        "coverage_fingerprint",
    )
    assert [r[0] for r in FAULT_RULES] == [
        f"RKT100{i}" for i in range(1, 7)
    ]


def test_fault_budget_gate_catches_coverage_shrink():
    from rocket_tpu.analysis import budgets as budgets_mod

    committed = {"crash_points": 66,
                 "coverage_fingerprint": "prefixes=66 save=21"}
    shrunk = {"crash_points": 50,
              "coverage_fingerprint": "prefixes=50 save=15"}
    findings = budgets_mod.diff_budget(
        "ckpt_protocol", committed, shrunk,
        keys=budgets_mod.FAULT_GATED_KEYS, rule="RKT1006", family="fault",
    )
    # numeric growth gating alone would wave a SHRINK through; the
    # fingerprint identity key is what refuses silent coverage loss
    assert rules_in(findings) == ["RKT1006"]
    assert any("coverage_fingerprint" in f.message for f in findings)


# -- multi-host skewed drain: torn layouts must not be resumable ------------


def _index_two_shards():
    return {"w": {
        "kind": "array", "shape": [8], "dtype": "float64",
        "chunks": [
            {"file": "shard_p0.npz", "key": "w:0", "index": [[0, 4]]},
            {"file": "shard_p1.npz", "key": "w:4", "index": [[4, 8]]},
        ],
    }}


def _write_rank(step_dir, process, local):
    checkpoint_io.write_snapshot(
        os.path.join(step_dir, "model_0"),
        {"process": process, "index": _index_two_shards(), "local": local},
    )


def test_skewed_drain_layouts_fall_back_to_last_complete_step(tmp_path):
    root = str(tmp_path)
    # Step 3: the last periodic save BOTH ranks completed.
    step3 = os.path.join(root, "3")
    _write_rank(step3, 0, {"w:0": np.arange(4.0)})
    _write_rank(step3, 1, {"w:4": np.arange(4.0, 8.0)})
    checkpoint_io.atomic_write(
        os.path.join(step3, "rng.json"), json.dumps({"c": 1}).encode())
    assert is_complete_checkpoint(step3)

    # Step 5: rank 0 drained here — wrote its shard, the index and the
    # rng marker, but rank 1 never drained at this step: its shard is
    # missing, so the index references a file that does not exist.
    step5 = os.path.join(root, "5")
    _write_rank(step5, 0, {"w:0": np.arange(4.0)})
    checkpoint_io.atomic_write(
        os.path.join(step5, "rng.json"), json.dumps({"c": 2}).encode())
    assert not is_complete_checkpoint(step5)

    # Step 7: rank 1 drained here — shard only, no index, no marker.
    step7 = os.path.join(root, "7")
    _write_rank(step7, 1, {"w:4": np.arange(4.0, 8.0)})
    assert not is_complete_checkpoint(step7)

    # Resume must skip BOTH torn layouts and land on step 3, and the
    # step it lands on must actually reassemble.
    assert newest_complete_step(root) == 3
    tree = checkpoint_io.load_pytree(os.path.join(step3, "model_0"))
    np.testing.assert_array_equal(tree["w"], np.arange(8.0))
