"""Byte-level BPE tokenizer: lossless round-trip, compression, persistence."""

import numpy as np

from rocket_tpu.data.text import BPETokenizer, synthetic_corpus


def test_bpe_roundtrip_and_compression():
    text = synthetic_corpus(num_chars=20_000)
    tok = BPETokenizer.train(text, vocab_size=512)
    assert tok.vocab_size <= 512
    ids = tok.encode(text)
    assert tok.decode(ids) == text  # lossless
    # Merges compress vs raw bytes.
    assert len(ids) < len(text.encode("utf-8")) * 0.8
    assert ids.dtype == np.int32 and int(ids.max()) < tok.vocab_size


def test_bpe_handles_unseen_bytes_and_unicode():
    tok = BPETokenizer.train("aaab aab ab  ab", vocab_size=260)
    s = "zzz é世 ab"  # bytes never seen in training
    assert tok.decode(tok.encode(s)) == s


def test_bpe_save_load(tmp_path):
    text = synthetic_corpus(num_chars=5_000)
    tok = BPETokenizer.train(text, vocab_size=300)
    path = str(tmp_path / "bpe.json")
    tok.save(path)
    tok2 = BPETokenizer.load(path)
    s = text[:500]
    np.testing.assert_array_equal(tok.encode(s), tok2.encode(s))
    assert tok2.vocab_size == tok.vocab_size


def test_bpe_vocab_size_floor():
    import pytest

    with pytest.raises(ValueError, match="vocab_size"):
        BPETokenizer.train("abc", vocab_size=100)
