"""Transformer LM: shapes, learnability, tensor-parallel + bf16 compile."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import CharTokenizer, TokenDataset, synthetic_corpus
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    next_token_loss,
)
from rocket_tpu.parallel.sharding import fsdp_rules, gpt2_tp_rules
from rocket_tpu.runtime.context import Runtime


def tiny_config(vocab=64):
    return TransformerConfig(
        vocab_size=vocab, max_seq_len=32, dim=32, num_layers=2, num_heads=4,
        dropout=0.0,
    )


def test_forward_shapes():
    model = TransformerLM(tiny_config())
    variables = model.init(jax.random.key(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    out, _ = model.apply(variables, {"tokens": tokens}, mode="eval")
    assert out["logits"].shape == (2, 16, 64)


def test_param_count_gpt2():
    model = TransformerLM(TransformerConfig.gpt2_124m())
    variables = model.init(jax.random.key(0))
    n = model.num_params(variables)
    # GPT-2 124M: 124,439,808 params (wte+wpe+12 blocks+ln_f, tied head).
    assert abs(n - 124_439_808) < 1_000_000, n


@pytest.mark.slow
def test_char_lm_learns(runtime8):
    corpus = synthetic_corpus(num_chars=40_000)
    tok = CharTokenizer(corpus)
    data = TokenDataset(tok.encode(corpus), seq_len=32)
    config = TransformerConfig(
        vocab_size=tok.vocab_size, max_seq_len=32, dim=64, num_layers=2,
        num_heads=4, dropout=0.0,
    )
    model = TransformerLM(config)
    losses = []

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.mode == "train" and attrs.looper.state.loss is not None:
                losses.append(float(np.asarray(attrs.looper.state.loss)))

    module = rt.Module(
        model,
        capsules=[
            rt.Loss(next_token_loss()),
            rt.Optimizer(optim.adamw(weight_decay=0.0)),
            rt.Scheduler(optim.constant_lr(3e-3)),
        ],
    )
    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=64, shuffle=True), module, Spy()],
                   tag="train", progress=False)],
        num_epochs=2,
        runtime=runtime8,
    ).launch()
    assert losses[-1] < losses[0] * 0.75, (losses[0], losses[-1])
    # Better than the uniform baseline ln(V).
    assert losses[-1] < np.log(tok.vocab_size) * 0.9


@pytest.mark.parametrize("rules", ["tp", "fsdp", "tp_llama"])
def test_sharded_training_compiles_and_runs(tmp_path, rules):
    runtime = Runtime(
        mesh_shape={"data": 8} if rules == "fsdp" else {"data": 4, "model": 2},
        seed=0,
        project_dir=str(tmp_path),
    )
    config = tiny_config()
    if rules == "tp_llama":
        # The second model family under tensor parallelism — notably the
        # separate swiglu gate/up projections sharding column-parallel.
        config.pos_embedding = "rope"
        config.norm = "rmsnorm"
        config.mlp = "swiglu"
        config.num_kv_heads = 2
    model = TransformerLM(config)
    rule_fn = fsdp_rules(min_size=0) if rules == "fsdp" else gpt2_tp_rules()
    rng = np.random.default_rng(0)
    data = TokenDataset(rng.integers(0, 64, size=4096).astype(np.int32), seq_len=32)
    module = rt.Module(
        model,
        capsules=[rt.Loss(next_token_loss()), rt.Optimizer(optim.adamw(), learning_rate=1e-3)],
        param_sharding=rule_fn,
        compute_dtype=jnp.bfloat16,
    )
    seen = {}

    class ShardSpy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            w = module.state["params"]["blocks"]["0"]["attn"]["qkv"]["w"]
            seen["spec"] = str(w.sharding.spec)
            # Adam moments mirror the param layout (ADVICE r1): a replicated
            # mu under TP/FSDP would cost ~2x model bytes per device.
            mu = module.state["opt_state"][0].mu
            seen["mu_spec"] = str(
                mu["blocks"]["0"]["attn"]["qkv"]["w"].sharding.spec
            )
            mlp_p = module.state["params"]["blocks"]["0"]["mlp"]
            if "fc_gate" in mlp_p:
                seen["gate_spec"] = str(mlp_p["fc_gate"]["w"].sharding.spec)

    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=16), module, ShardSpy()],
                   tag="train", progress=False)],
        num_epochs=1,
        runtime=runtime,
    ).launch()
    # Params kept their sharded layout through training.
    if rules in ("tp", "tp_llama"):
        assert "model" in seen["spec"], seen
        assert "model" in seen["mu_spec"], seen
        if rules == "tp_llama":
            # The new fc_gate rule actually sharded the gate kernel.
            assert "model" in seen["gate_spec"], seen
    else:
        assert "data" in seen["mu_spec"], seen


def test_token_dataset_windows():
    tokens = np.arange(100, dtype=np.int32)
    ds = TokenDataset(tokens, seq_len=10)
    assert len(ds) == 10
    np.testing.assert_array_equal(ds[1]["tokens"], np.arange(10, 20))
    batch = ds.get_batch(np.asarray([0, 2]))
    assert batch["tokens"].shape == (2, 10)
    np.testing.assert_array_equal(batch["tokens"][1], np.arange(20, 30))


def _train_losses(tmp_path, mesh_shape, attention_impl, tag, **config_kw):
    """Short training run, returns the per-step losses (VERDICT r1 item 5:
    ring-attention sequence parallelism must match the unsharded run)."""
    n_dev = int(np.prod(list(mesh_shape.values())))
    runtime = Runtime(
        mesh_shape=mesh_shape,
        devices=jax.devices()[:n_dev],
        seed=0,
        project_dir=str(tmp_path),
    )
    config = TransformerConfig(
        vocab_size=64, max_seq_len=32, dim=32, num_layers=2, num_heads=4,
        dropout=0.0, attention_impl=attention_impl, **config_kw,
    )
    model = TransformerLM(config)
    rng = np.random.default_rng(0)
    data = TokenDataset(rng.integers(0, 64, size=33 * 64).astype(np.int32), seq_len=32)
    losses = []

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.looper.state.loss is not None:
                losses.append(float(np.asarray(attrs.looper.state.loss)))

    module = rt.Module(
        model,
        capsules=[rt.Loss(next_token_loss()), rt.Optimizer(optim.adam(), learning_rate=1e-3)],
    )
    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=16, drop_last=True), module, Spy()],
                   tag=tag, progress=False)],
        num_epochs=1,
        runtime=runtime,
    ).launch()
    return losses


@pytest.mark.slow
def test_ring_attention_matches_unsharded_training(tmp_path):
    """Same seed, same data: seq sharded over 4 devices (ring) vs one-axis
    data-parallel (xla attention) — losses must agree to fp tolerance."""
    ring = _train_losses(tmp_path / "ring", {"data": 2, "seq": 4}, "ring", "train")
    base = _train_losses(tmp_path / "base", {"data": 2}, "xla", "train")
    assert len(ring) == len(base) and len(ring) >= 4
    np.testing.assert_allclose(ring, base, rtol=2e-4, atol=2e-5)


@pytest.mark.slow
def test_ring_attention_with_rope_matches_unsharded(tmp_path):
    """RoPE composes with ring: rotations run on the GSPMD-global view with
    global positions, so seq-sharded losses match the unsharded run."""
    ring = _train_losses(tmp_path / "ring", {"data": 2, "seq": 4}, "ring",
                         "train", pos_embedding="rope", norm="rmsnorm")
    base = _train_losses(tmp_path / "base", {"data": 2}, "xla",
                         "train", pos_embedding="rope", norm="rmsnorm")
    assert len(ring) == len(base) and len(ring) >= 4
    np.testing.assert_allclose(ring, base, rtol=2e-4, atol=2e-5)


def test_scan_layers_matches_looped_forward():
    """scan_layers compiles ONE block body over stacked params; its logits
    must match the looped model given identical params."""
    import dataclasses

    config = tiny_config()
    loop_model = TransformerLM(config)
    scan_model = TransformerLM(dataclasses.replace(config, scan_layers=True))
    variables = loop_model.init(jax.random.key(0))
    # Stack the looped per-layer params into the scan layout.
    per_block = [variables["params"]["blocks"][str(i)] for i in range(config.num_layers)]
    scan_params = {k: v for k, v in variables["params"].items() if k != "blocks"}
    scan_params["blocks_stacked"] = jax.tree.map(lambda *xs: jnp.stack(xs), *per_block)

    tokens = {"tokens": jnp.asarray(np.random.default_rng(0).integers(0, 64, (2, 16)), jnp.int32)}
    out_loop, _ = loop_model.apply(variables, tokens, mode="eval")
    out_scan, _ = scan_model.apply(
        {"params": scan_params, "state": {}}, tokens, mode="eval"
    )
    np.testing.assert_allclose(
        np.asarray(out_loop["logits"]), np.asarray(out_scan["logits"]),
        rtol=1e-5, atol=1e-5,
    )
    # init() in scan mode produces the same stacked values directly.
    direct = scan_model.init(jax.random.key(0))["params"]["blocks_stacked"]
    for a, b in zip(jax.tree.leaves(direct), jax.tree.leaves(scan_params["blocks_stacked"])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


@pytest.mark.slow
def test_scan_layers_trains_with_tp_rules(tmp_path):
    """Stacked params + left-padded TP specs: one training epoch on a
    ('data','model') mesh keeps the stacked QKV sharded over 'model'."""
    import dataclasses

    runtime = Runtime(mesh_shape={"data": 4, "model": 2}, seed=0,
                      project_dir=str(tmp_path))
    config = dataclasses.replace(tiny_config(), scan_layers=True)
    model = TransformerLM(config)
    rng = np.random.default_rng(0)
    data = TokenDataset(rng.integers(0, 64, size=4096).astype(np.int32), seq_len=32)
    module = rt.Module(
        model,
        capsules=[rt.Loss(next_token_loss()),
                  rt.Optimizer(optim.adamw(), learning_rate=1e-3)],
        param_sharding=gpt2_tp_rules(),
    )
    seen = {}

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            w = module.state["params"]["blocks_stacked"]["attn"]["qkv"]["w"]
            seen["ndim"], seen["spec"] = w.ndim, str(w.sharding.spec)

    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=16), module, Spy()], tag="train",
                   progress=False)],
        num_epochs=1,
        runtime=runtime,
    ).launch()
    assert seen["ndim"] == 3 and "model" in seen["spec"], seen


def test_generate_shapes_determinism_and_range():
    from rocket_tpu.models.transformer import generate

    config = tiny_config()
    model = TransformerLM(config)
    variables = model.init(jax.random.key(0))
    prompt = np.array([[1, 2, 3]], np.int32)

    greedy1 = generate(model, variables, prompt, 8, temperature=0)
    greedy2 = generate(model, variables, prompt, 8, temperature=0)
    assert greedy1.shape == (1, 11)
    np.testing.assert_array_equal(np.asarray(greedy1), np.asarray(greedy2))
    np.testing.assert_array_equal(np.asarray(greedy1[:, :3]), prompt)
    assert int(jnp.max(greedy1)) < config.vocab_size and int(jnp.min(greedy1)) >= 0

    s1 = generate(model, variables, prompt, 8, key=jax.random.key(1), top_k=8)
    s2 = generate(model, variables, prompt, 8, key=jax.random.key(2), top_k=8)
    assert s1.shape == (1, 11)
    assert not np.array_equal(np.asarray(s1), np.asarray(s2))  # keys differ

    with pytest.raises(ValueError, match="needs a PRNG key"):
        generate(model, variables, prompt, 4)
    with pytest.raises(ValueError, match="exceed"):
        generate(model, variables, prompt, config.max_seq_len)


@pytest.mark.slow
def test_pipeline_parallel_matches_looped_model(tmp_path):
    """GPipe trunk over a ('data','pipe') mesh: logits match the plain
    looped model, and a training epoch runs with pipeline_rules sharding."""
    import dataclasses

    from rocket_tpu.parallel.sharding import pipeline_rules

    runtime = Runtime(mesh_shape={"data": 2, "pipe": 4}, seed=0,
                      project_dir=str(tmp_path))
    base = TransformerConfig(
        vocab_size=64, max_seq_len=32, dim=32, num_layers=4, num_heads=4,
        dropout=0.0,
    )
    loop_model = TransformerLM(base)
    pipe_model = TransformerLM(dataclasses.replace(
        base, scan_layers=True, pipeline_axis="pipe", pipeline_microbatches=2,
    ))
    variables = loop_model.init(jax.random.key(0))
    per_block = [variables["params"]["blocks"][str(i)] for i in range(4)]
    pipe_params = {k: v for k, v in variables["params"].items() if k != "blocks"}
    pipe_params["blocks_stacked"] = jax.tree.map(lambda *xs: jnp.stack(xs), *per_block)

    tokens = {"tokens": jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (8, 32)), jnp.int32)}
    out_loop, _ = loop_model.apply(variables, tokens, mode="eval")
    out_pipe, _ = pipe_model.apply(
        {"params": pipe_params, "state": {}}, tokens, mode="eval"
    )
    np.testing.assert_allclose(
        np.asarray(out_loop["logits"]), np.asarray(out_pipe["logits"]),
        rtol=2e-4, atol=2e-4,
    )

    # End-to-end training with the stacked layers sharded over 'pipe'.
    rng = np.random.default_rng(0)
    data = TokenDataset(rng.integers(0, 64, size=32 * 33).astype(np.int32), seq_len=32)
    module = rt.Module(
        TransformerLM(dataclasses.replace(
            base, scan_layers=True, pipeline_axis="pipe", pipeline_microbatches=2,
        )),
        capsules=[rt.Loss(next_token_loss()),
                  rt.Optimizer(optim.adamw(), learning_rate=1e-3)],
        param_sharding=pipeline_rules(),
    )
    seen = {}

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            w = module.state["params"]["blocks_stacked"]["attn"]["qkv"]["w"]
            seen["spec"] = str(w.sharding.spec)

    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=16, drop_last=True), module, Spy()],
                   tag="train", progress=False)],
        num_epochs=1,
        runtime=runtime,
    ).launch()
    assert "pipe" in seen["spec"], seen


def test_pipeline_requires_scan_layers():
    import dataclasses

    config = dataclasses.replace(tiny_config(), pipeline_axis="pipe")
    model = TransformerLM(config)
    variables = model.init(jax.random.key(0))
    with pytest.raises(RuntimeError, match="scan_layers"):
        model.apply(variables, {"tokens": jnp.zeros((4, 16), jnp.int32)}, mode="eval")


@pytest.mark.slow
@pytest.mark.parametrize("scan", [False, True])
def test_cached_generation_matches_recompute(scan):
    """KV-cached decode (O(T) per token) must produce exactly the same
    tokens as the full-prefix recompute path — greedy AND sampled (per-step
    keys are position-derived, so the streams align)."""
    import dataclasses

    config = dataclasses.replace(tiny_config(), scan_layers=scan)
    model = TransformerLM(config)
    variables = model.init(jax.random.key(0))
    from rocket_tpu.models.transformer import generate

    prompt = np.array([[3, 1, 4, 1], [2, 7, 1, 8]], np.int32)
    for kwargs in (
        dict(temperature=0),
        dict(key=jax.random.key(5), temperature=0.9, top_k=10),
        dict(key=jax.random.key(6), temperature=0.9, top_p=0.8),
    ):
        cached = generate(model, variables, prompt, 10, use_cache=True, **kwargs)
        full = generate(model, variables, prompt, 10, use_cache=False, **kwargs)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(full))


@pytest.mark.slow
@pytest.mark.parametrize("tied,scan", [(True, False), (False, False), (True, True), (False, True)])
def test_fused_loss_chunk_matches_full_logits(tied, scan):
    """loss_chunk (chunked head+CE, no logits materialization) must be a
    pure optimization: same loss and same grads as the full-logits path —
    including on the scan-over-layers trunk."""
    cfg = tiny_config()
    cfg.tied_embeddings = tied
    cfg.scan_layers = scan
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    batch = {"tokens": tokens}
    objective = next_token_loss()

    def loss_with(chunk, params):
        cfg.loss_chunk = chunk
        out, _ = model.apply(
            {"params": params, "state": variables["state"]}, batch, mode="train"
        )
        return objective(out)

    full, g_full = jax.value_and_grad(lambda p: loss_with(0, p))(
        variables["params"]
    )
    fused, g_fused = jax.value_and_grad(lambda p: loss_with(8, p))(
        variables["params"]
    )
    np.testing.assert_allclose(float(fused), float(full), rtol=1e-5)
    jax.tree.map(
        lambda a, b: np.testing.assert_allclose(a, b, rtol=1e-4, atol=1e-5),
        g_full, g_fused,
    )


def test_fused_loss_chunk_skips_eval_and_ragged():
    cfg = tiny_config()
    cfg.loss_chunk = 8
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0))
    tokens = jnp.zeros((2, 16), jnp.int32)
    out, _ = model.apply(variables, {"tokens": tokens}, mode="eval")
    assert "logits" in out and "nll" not in out  # eval keeps logits
    ragged = jnp.zeros((2, 13), jnp.int32)  # 13 % 8 != 0 -> full path
    out, _ = model.apply(variables, {"tokens": ragged}, mode="train")
    assert "logits" in out and "nll" not in out


@pytest.mark.slow
def test_generate_top_p_restricts_to_nucleus():
    """With a peaked distribution and small top_p, sampling must collapse
    to the argmax token; top_p=1.0 must match unfiltered sampling."""
    from rocket_tpu.models.transformer import generate

    cfg = tiny_config()
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0))
    prompt = jnp.zeros((2, 4), jnp.int32)

    greedy = generate(model, variables, prompt, 8, temperature=0.0)
    # Tiny temperature -> distribution is sharply peaked; top_p=0.1 keeps
    # only the top token, so the sample must equal greedy decoding.
    nucleus = generate(
        model, variables, prompt, 8,
        key=jax.random.key(1), temperature=0.05, top_p=0.1,
    )
    np.testing.assert_array_equal(np.asarray(nucleus), np.asarray(greedy))

    full = generate(
        model, variables, prompt, 8, key=jax.random.key(2), temperature=1.0,
    )
    loose = generate(
        model, variables, prompt, 8, key=jax.random.key(2), temperature=1.0,
        top_p=1.0,
    )
    np.testing.assert_array_equal(np.asarray(full), np.asarray(loose))
    assert nucleus.shape == (2, 12)


@pytest.mark.slow
def test_gqa_lm_trains_and_generates():
    """num_kv_heads < num_heads: forward, grads, and cached-vs-recompute
    generation parity all hold on the grouped attention path."""
    from rocket_tpu.models.transformer import generate

    cfg = tiny_config()
    cfg.num_kv_heads = 2  # 4 query heads, groups of 2
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)

    def loss(params):
        out, _ = model.apply(
            {"params": params, "state": {}}, {"tokens": tokens}, mode="train"
        )
        return next_token_loss()(out)

    val, grads = jax.value_and_grad(loss)(variables["params"])
    assert np.isfinite(float(val))
    gn = sum(float(jnp.sum(jnp.abs(g))) for g in jax.tree.leaves(grads))
    assert gn > 0.0

    prompt = np.array([[3, 1, 4, 1]], np.int32)
    cached = generate(model, variables, prompt, 8, use_cache=True,
                      key=jax.random.key(2), temperature=0.9)
    full = generate(model, variables, prompt, 8, use_cache=False,
                    key=jax.random.key(2), temperature=0.9)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(full))


def test_llama_style_lm_trains_and_generates():
    """The Llama-family knobs — rope + rmsnorm + swiglu + GQA, untied head —
    compose: finite loss, flowing grads, no wpe params, and cached decode
    exactly matches full recompute (the RoPE offset logic in the cache
    path)."""
    from rocket_tpu.models.transformer import generate

    cfg = tiny_config()
    cfg.pos_embedding = "rope"
    cfg.norm = "rmsnorm"
    cfg.mlp = "swiglu"
    cfg.num_kv_heads = 2
    cfg.tied_embeddings = False
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0))
    assert "wpe" not in variables["params"]  # rope has no learned positions
    assert "bias" not in variables["params"]["ln_f"]  # rmsnorm: scale only
    mlp_params = variables["params"]["blocks"]["0"]["mlp"]
    assert mlp_params["fc_in"]["w"].shape == (32, 4 * 32)   # up projection
    assert mlp_params["fc_gate"]["w"].shape == (32, 4 * 32)  # gate projection

    tokens = jax.random.randint(jax.random.key(1), (2, 16), 0, cfg.vocab_size)

    def loss(params):
        out, _ = model.apply(
            {"params": params, "state": {}}, {"tokens": tokens}, mode="train"
        )
        return next_token_loss()(out)

    val, grads = jax.value_and_grad(loss)(variables["params"])
    assert np.isfinite(float(val))
    assert all(np.isfinite(np.asarray(g)).all() for g in jax.tree.leaves(grads))

    prompt = np.array([[3, 1, 4, 1], [2, 7, 1, 8]], np.int32)
    for kwargs in (dict(temperature=0),
                   dict(key=jax.random.key(2), temperature=0.9)):
        cached = generate(model, variables, prompt, 10, use_cache=True, **kwargs)
        full = generate(model, variables, prompt, 10, use_cache=False, **kwargs)
        np.testing.assert_array_equal(np.asarray(cached), np.asarray(full))


def test_rope_is_relative_under_shift():
    """RoPE attention logits depend only on relative positions: rotating
    q/k with offset 0 vs offset 7 gives identical causal attention output."""
    from rocket_tpu.nn.attention import apply_rope, dot_product_attention

    k0 = jax.random.key(3)
    q = jax.random.normal(jax.random.fold_in(k0, 0), (1, 2, 8, 8))
    k = jax.random.normal(jax.random.fold_in(k0, 1), (1, 2, 8, 8))
    v = jax.random.normal(jax.random.fold_in(k0, 2), (1, 2, 8, 8))
    out0 = dot_product_attention(apply_rope(q, 0), apply_rope(k, 0), v)
    out7 = dot_product_attention(apply_rope(q, 7), apply_rope(k, 7), v)
    np.testing.assert_allclose(np.asarray(out0), np.asarray(out7), atol=1e-5)


def test_label_smoothing_matches_on_both_loss_paths():
    """config.label_smoothing gives identical losses on the fused
    (loss_chunk) and full-logits paths, and matches the optax smoothed CE."""
    import optax

    cfg = tiny_config()
    cfg.label_smoothing = 0.1
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0))
    tokens = jax.random.randint(jax.random.key(1), (2, 32), 0, cfg.vocab_size)
    batch = {"tokens": tokens}
    obj = next_token_loss()

    cfg.loss_chunk = 0
    out_full, _ = model.apply(variables, batch, mode="train")
    full = float(obj(out_full))
    cfg.loss_chunk = 8
    out_fused, _ = model.apply(variables, batch, mode="train")
    fused = float(obj(out_fused))
    np.testing.assert_allclose(fused, full, rtol=1e-5)

    # Reference: optax smooth_labels + soft CE on the same logits.
    logits = out_full["logits"][:, :-1].astype(jnp.float32)
    targets = jax.nn.one_hot(tokens[:, 1:], cfg.vocab_size)
    smoothed = optax.smooth_labels(targets, 0.1)
    ref = float(optax.softmax_cross_entropy(logits, smoothed).mean())
    np.testing.assert_allclose(full, ref, rtol=1e-5)

    # Eval stays plain CE (comparable to log-perplexity).
    out_eval, _ = model.apply(variables, batch, mode="eval")
    assert "label_smoothing" not in out_eval

    with pytest.raises(ValueError, match="label_smoothing"):
        bad = tiny_config()
        bad.label_smoothing = 1.0
        TransformerLM(bad)


@pytest.mark.slow
def test_generate_eos_freezes_finished_sequences():
    """Once a sequence samples eos_token_id, all its later positions are
    eos; other sequences keep generating; eos in the PROMPT doesn't count."""
    from rocket_tpu.models.transformer import generate

    cfg = tiny_config()
    model = TransformerLM(cfg)
    variables = model.init(jax.random.key(0))
    eos = 5
    # Prompt CONTAINS the eos token — must not freeze from position 0.
    prompt = np.array([[eos, 1, 4, 1], [2, 7, 1, 8]], np.int32)
    # key(14)/temperature=1.5 chosen so row 0 demonstrably samples EOS
    # mid-generation (searched once, pinned — a vacuous no-EOS run would
    # fail the hits assertion below). Provenance: the original key(4) pin
    # was searched against the pre-sampling-core `_sample_token`; its
    # trajectory had already drifted before the serving PR landed
    # (verified failing on that PR's parent commit), so the expectation
    # is re-pinned against the now-canonical shared sampling core
    # (models/sampling.py, scalar path): keys 0..39 re-searched on it,
    # first mid-sequence hit pinned. The assertions below are about EOS
    # FREEZING semantics, not about which token a given key samples —
    # any key with a mid-sequence hit exercises them fully.
    out = np.asarray(generate(
        model, variables, prompt, 12, key=jax.random.key(14),
        temperature=1.5, eos_token_id=eos,
    ))
    gen0 = out[0, 4:]
    assert gen0[0] != eos  # prompt EOS did NOT freeze generation
    hits = np.where(gen0 == eos)[0]
    assert hits.size and 0 < hits[0] < len(gen0) - 1, gen0
    np.testing.assert_array_equal(gen0[hits[0]:], eos)  # frozen after EOS
    # Parity between cache and recompute paths holds with eos freezing too.
    cached = generate(model, variables, prompt, 8, key=jax.random.key(4),
                      temperature=0.9, eos_token_id=eos, use_cache=True)
    full = generate(model, variables, prompt, 8, key=jax.random.key(4),
                    temperature=0.9, eos_token_id=eos, use_cache=False)
    np.testing.assert_array_equal(np.asarray(cached), np.asarray(full))


@pytest.mark.slow
@pytest.mark.parametrize("dropout,family", [
    (0.0, "gpt2"), (0.2, "gpt2"), (0.0, "llama"),
])
def test_1f1b_matches_gpipe_loss_and_grads(tmp_path, dropout, family):
    """pipeline_schedule='1f1b' (fused fwd+bwd, O(P) activations) must
    produce the same loss and param grads as the autodiff'd GPipe path on
    the same params/batch (virtual ('data','pipe') mesh). WITH dropout the
    schedules must still agree exactly: both derive masks from
    fold_in(rng, microbatch, data-shard, layer), and the 1F1B backward
    replays the same keys when it recomputes the stage forward."""
    import dataclasses

    extra = (
        # Llama-family knobs: RoPE + RMSNorm + SwiGLU + GQA + UNTIED head
        # — exercises the 1F1B tail's separate-head branch.
        dict(num_kv_heads=2, pos_embedding="rope", norm="rmsnorm",
             mlp="swiglu", tied_embeddings=False)
        if family == "llama" else {}
    )
    base = TransformerConfig(
        vocab_size=64, max_seq_len=32, dim=32, num_layers=4, num_heads=4,
        dropout=dropout, scan_layers=True, pipeline_axis="pipe",
        pipeline_microbatches=4, **extra,
    )
    tokens = jnp.asarray(
        np.random.default_rng(3).integers(0, 64, (8, 32)), jnp.int32
    )
    objective = next_token_loss()
    rng = jax.random.key(7) if dropout else None

    def loss_and_grads(schedule):
        runtime = Runtime(mesh_shape={"data": 2, "pipe": 4}, seed=0,
                          project_dir=str(tmp_path))
        model = TransformerLM(
            dataclasses.replace(base, pipeline_schedule=schedule)
        )
        variables = model.init(jax.random.key(0))
        if schedule == "1f1b":
            vag = model.pipelined_value_and_grad(objective)
            assert vag is not None
            (loss, _), grads = jax.jit(vag)(
                variables["params"], variables["state"], {"tokens": tokens},
                rng,
            )
            return loss, grads

        assert model.pipelined_value_and_grad(objective) is None  # gpipe

        def f(p):
            out, _ = model.apply(
                {"params": p, "state": {}}, {"tokens": tokens},
                mode="train", rng=rng,
            )
            return objective(out)

        return jax.jit(jax.value_and_grad(f))(variables["params"])

    l_ref, g_ref = loss_and_grads("gpipe")
    l_new, g_new = loss_and_grads("1f1b")
    np.testing.assert_allclose(float(l_ref), float(l_new), rtol=1e-5)
    flat_ref = jax.tree_util.tree_flatten_with_path(g_ref)[0]
    flat_new = dict(
        (jax.tree_util.keystr(kp), v)
        for kp, v in jax.tree_util.tree_flatten_with_path(g_new)[0]
    )
    assert set(flat_new) == {jax.tree_util.keystr(kp) for kp, _ in flat_ref}
    for kp, ref in flat_ref:
        new = flat_new[jax.tree_util.keystr(kp)]
        np.testing.assert_allclose(
            np.asarray(ref, np.float32), np.asarray(new, np.float32),
            rtol=2e-4, atol=2e-4, err_msg=jax.tree_util.keystr(kp),
        )


@pytest.mark.slow
def test_1f1b_memory_bounded_in_microbatches(tmp_path):
    """The verdict's O(P)-vs-O(M) claim, asserted via compiled memory
    analysis AT FIXED MICROBATCH SIZE (batch grows with M — growing M at
    fixed global batch shrinks the microbatch, which hides the saved-
    activation term): GPipe must buffer all M stage inputs across the
    fwd/bwd boundary, 1F1B's rotating buffer holds 2P-1 regardless of M.
    Both schedules carry identical O(B) input/output/dx terms, so the
    M-slope DIFFERENCE isolates the saved-activation growth."""
    import dataclasses

    base = TransformerConfig(
        vocab_size=64, max_seq_len=64, dim=64, num_layers=4, num_heads=4,
        dropout=0.0, scan_layers=True, pipeline_axis="pipe",
    )
    mb_rows, seq = 2, 64
    objective = next_token_loss()

    def temp_bytes(schedule, m):
        runtime = Runtime(mesh_shape={"pipe": 4}, seed=0,
                          devices=jax.devices()[:4],
                          project_dir=str(tmp_path))
        tokens = jnp.asarray(
            np.random.default_rng(3).integers(0, 64, (mb_rows * m, seq)),
            jnp.int32,
        )
        model = TransformerLM(dataclasses.replace(
            base, pipeline_schedule=schedule, pipeline_microbatches=m,
        ))
        variables = model.init(jax.random.key(0))
        if schedule == "1f1b":
            vag = model.pipelined_value_and_grad(objective)
            fn = jax.jit(lambda p: vag(p, {}, {"tokens": tokens}, None)[0][0])
        else:
            def fn_(p):
                out, _ = model.apply(
                    {"params": p, "state": {}}, {"tokens": tokens},
                    mode="train",
                )
                return objective(out)
            fn = jax.jit(jax.value_and_grad(fn_))
        compiled = fn.lower(variables["params"]).compile()
        return compiled.memory_analysis().temp_size_in_bytes

    gpipe_growth = temp_bytes("gpipe", 16) - temp_bytes("gpipe", 4)
    f1b_growth = temp_bytes("1f1b", 16) - temp_bytes("1f1b", 4)
    # Shared O(B) terms cancel in the growth difference; what remains is
    # GPipe's 12 extra saved microbatch activations (each mb_rows x T x D
    # x 4B plus per-layer residual slack) vs 1F1B's fixed-depth buffer.
    unit = mb_rows * seq * base.dim * 4
    assert gpipe_growth - f1b_growth > 6 * unit, (f1b_growth, gpipe_growth)
    # And independently: 1F1B's own per-M slope stays under half of
    # GPipe's (the rotating buffer does not scale with M; 1F1B's residual
    # growth is the shared O(B) input/dx terms only).
    assert f1b_growth < gpipe_growth / 2, (f1b_growth, gpipe_growth)
