"""Checkpoint save/resume: layout, state equivalence, mid-epoch fast-forward."""

import os

import numpy as np
import optax
import pytest

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.runtime.context import Runtime


def make_dataset(n=256, dim=8, classes=4, seed=0):
    rng = np.random.default_rng(seed)
    centers = rng.normal(size=(classes, dim)) * 3.0
    labels = rng.integers(0, classes, size=n)
    images = centers[labels] + rng.normal(size=(n, dim)) * 0.5
    return [
        {"image": images[i].astype(np.float32), "label": np.int32(labels[i])}
        for i in range(n)
    ]


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def build(runtime, model, data, ckpt_dir, num_epochs, save_every=4, resume_from=None):
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    return rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=32),
                    module,
                    rt.Checkpointer(
                        output_dir=ckpt_dir,
                        save_every=save_every,
                        resume_from=resume_from,
                    ),
                ],
                tag="train",
            )
        ],
        num_epochs=num_epochs,
        statefull=True,
        runtime=runtime,
    ), module


def test_checkpoint_layout_written(tmp_path):
    runtime = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    data = make_dataset()
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    ckpt = str(tmp_path / "ckpts")
    tree, _ = build(runtime, model, data, ckpt, num_epochs=1)
    tree.launch()
    # 256/32 = 8 iterations, save_every=4 -> steps 4 and 8
    assert sorted(os.listdir(ckpt)) == ["4", "8"]
    step_dir = os.path.join(ckpt, "8")
    assert set(os.listdir(step_dir)) == {"model_0", "capsules.pkl", "rng.json"}
    # Sharded pickle-free layout: one npz per host + a JSON chunk index.
    assert set(os.listdir(os.path.join(step_dir, "model_0"))) == {
        "shard_p0.npz",
        "index.json",
    }


def test_resume_restores_params_and_counters(tmp_path):
    data = make_dataset()
    ckpt = str(tmp_path / "ckpts")

    runtime1 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    model1 = MLP(in_features=8, num_classes=4, hidden=(16,))
    tree1, module1 = build(runtime1, model1, data, ckpt, num_epochs=1)
    tree1.launch()
    # state after the run (model registry is cleared at destroy; keep a copy)
    # -> re-read from the written checkpoint instead
    from rocket_tpu.runtime.checkpoint_io import load_pytree

    saved = load_pytree(os.path.join(ckpt, "8", "model_0"))

    runtime2 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    model2 = MLP(in_features=8, num_classes=4, hidden=(16,))
    tree2, module2 = build(
        runtime2, model2, data, ckpt, num_epochs=2, resume_from=os.path.join(ckpt, "8")
    )
    attrs = rt.Attributes()
    tree2.setup(attrs)
    restored = module2.state
    np.testing.assert_allclose(
        saved["params/1/w"],
        np.asarray(restored["params"]["1"]["w"]),
    )
    assert int(np.asarray(restored["step"])) == 8
    # The save fired DURING epoch 0 (at its last iteration), so resume lands
    # mid-epoch: epoch 0 with 8 batches already consumed.
    assert tree2.state_dict()["epoch_idx"] == 0
    # The Checkpointer runs inside the dispatch wave, before the Looper
    # advances its counter: Looper saved 7 while the Dataset saved 8. On
    # resume the Dataset's skip is authoritative — the Looper's one extra
    # wave no-ops via terminate, so the data stream stays exact.
    looper = tree2.capsules[0]
    assert looper.state_dict()["batch_idx"] == 7
    tree2.destroy(attrs)


def test_resume_capsules_false_skips_capsule_state(tmp_path):
    data = make_dataset()
    ckpt = str(tmp_path / "ckpts")
    runtime1 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    model1 = MLP(in_features=8, num_classes=4, hidden=(16,))
    tree1, _ = build(runtime1, model1, data, ckpt, num_epochs=1)
    tree1.launch()

    runtime2 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    model2 = MLP(in_features=8, num_classes=4, hidden=(16,))
    module2 = rt.Module(
        model2,
        capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    ckpointer = rt.Checkpointer(
        output_dir=ckpt, save_every=1000, resume_from=os.path.join(ckpt, "8"),
        resume_capsules=False,
    )
    tree2 = rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=32), module2, ckpointer], tag="train")],
        num_epochs=1,
        statefull=True,
        runtime=runtime2,
    )
    attrs = rt.Attributes()
    tree2.setup(attrs)
    # model weights restored, but launcher epoch counter untouched
    assert int(np.asarray(module2.state["step"])) == 8
    assert tree2.state_dict()["epoch_idx"] == 0
    tree2.destroy(attrs)


def test_sharded_save_is_gather_free_and_reshards(tmp_path, monkeypatch):
    """TP-sharded state saves with NO process_allgather and restores
    bit-exact under a *different* layout (VERDICT r1 item 4)."""
    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from rocket_tpu.runtime.checkpoint_io import load_pytree, save_pytree

    def boom(*a, **k):
        raise AssertionError("save path must not gather across hosts")

    monkeypatch.setattr(multihost_utils, "process_allgather", boom)

    runtime = Runtime(mesh_shape={"data": 2, "model": 4}, project_dir=str(tmp_path))
    rng = np.random.default_rng(0)
    w = rng.normal(size=(16, 32)).astype(np.float32)
    b = rng.normal(size=(32,)).astype(np.float32)
    tree = {
        "params": {
            "w": jax.device_put(w, runtime.sharding(None, "model")),
            "b": jax.device_put(b, runtime.sharding("model")),
        },
        "step": jnp.asarray(7),
        "note": "plain-json-leaf",
    }
    path = str(tmp_path / "ckpt")
    save_pytree(path, tree)

    # Restore under a transposed layout (row-parallel w, replicated b).
    template = {
        "params": {
            "w": jax.device_put(np.zeros_like(w), runtime.sharding("model", None)),
            "b": jax.device_put(np.zeros_like(b), runtime.replicated),
        },
        "step": jnp.asarray(0),
        "note": "",
    }
    out = load_pytree(path, template)
    np.testing.assert_array_equal(np.asarray(out["params"]["w"]), w)
    np.testing.assert_array_equal(np.asarray(out["params"]["b"]), b)
    assert out["params"]["w"].sharding == template["params"]["w"].sharding
    assert int(out["step"]) == 7
    assert out["note"] == "plain-json-leaf"

    # Flat introspection load (no template) assembles full arrays.
    flat = load_pytree(path)
    np.testing.assert_array_equal(flat["params/w"], w)
    assert flat["note"] == "plain-json-leaf"


def test_keep_last_prunes_old_checkpoints(tmp_path):
    runtime = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    data = make_dataset()
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    ckpt = str(tmp_path / "ckpts")
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    tree = rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=32),
                    module,
                    rt.Checkpointer(output_dir=ckpt, save_every=2, keep_last=2),
                ],
                tag="train",
            )
        ],
        num_epochs=1,
        runtime=runtime,
    )
    tree.launch()
    assert sorted(os.listdir(ckpt), key=int) == ["6", "8"]


def test_mid_epoch_resume_with_device_cache(tmp_path):
    """Resume lands mid-epoch with the device-resident cache active: the
    restored Dataset fast-forwards the cached loader, and the remaining data
    stream matches the uninterrupted run (VERDICT r1 weak item 8)."""
    data = make_dataset(n=256)
    ckpt = str(tmp_path / "ckpts")

    def build_spy(runtime, model, resume_from=None):
        seen = []

        class BatchSpy(rt.Capsule):
            def __init__(self):
                super().__init__(priority=999)  # right after Dataset

            def launch(self, attrs=None):
                if attrs.batch is not None:
                    seen.append(np.asarray(attrs.batch["label"]).copy())

        module = rt.Module(
            model,
            capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)],
        )
        ds = rt.Dataset(data, batch_size=32, device_cache=True)
        tree = rt.Launcher(
            [
                rt.Looper(
                    [ds, module, BatchSpy(),
                     rt.Checkpointer(output_dir=ckpt, save_every=3,
                                     resume_from=resume_from)],
                    tag="train", progress=False,
                )
            ],
            num_epochs=1,
            statefull=True,
            runtime=runtime,
        )
        return tree, ds, seen

    runtime1 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    tree1, ds1, seen1 = build_spy(
        runtime1, MLP(in_features=8, num_classes=4, hidden=(16,))
    )
    assert ds1 is not None
    tree1.launch()
    assert len(seen1) == 8  # 256/32 batches, device cache active

    # Resume from the step-3 checkpoint: Dataset batch_idx=3 -> batches 3..7.
    runtime2 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    tree2, ds2, seen2 = build_spy(
        runtime2, MLP(in_features=8, num_classes=4, hidden=(16,)),
        resume_from=os.path.join(ckpt, "3"),
    )
    tree2.launch()
    # The resumed stream replays exactly the uninterrupted run's tail.
    assert len(seen2) == len(seen1) - 3
    for a, b in zip(seen2, seen1[3:]):
        np.testing.assert_array_equal(a, b)


def test_resume_from_latest(tmp_path):
    """resume_from="latest" restores the newest complete checkpoint — the
    restart-after-preemption idiom."""
    data = make_dataset()
    ckpt = str(tmp_path / "ckpts")
    runtime1 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    model1 = MLP(in_features=8, num_classes=4, hidden=(16,))
    tree1, _ = build(runtime1, model1, data, ckpt, num_epochs=1)
    tree1.launch()  # writes steps 4 and 8

    runtime2 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    model2 = MLP(in_features=8, num_classes=4, hidden=(16,))
    tree2, module2 = build(
        runtime2, model2, data, ckpt, num_epochs=2, resume_from="latest"
    )
    attrs = rt.Attributes()
    tree2.setup(attrs)
    assert int(np.asarray(module2.state["step"])) == 8
    tree2.destroy(attrs)

    # No checkpoint yet -> fresh start (a relauncher can ALWAYS pass
    # resume_from="latest"); a torn step dir is skipped for the previous
    # complete one.
    runtime3 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    model3 = MLP(in_features=8, num_classes=4, hidden=(16,))
    tree3, module3 = build(
        runtime3, model3, data, str(tmp_path / "nope"), num_epochs=1,
        resume_from="latest",
    )
    tree3.setup(rt.Attributes())  # no raise
    assert int(np.asarray(module3.state["step"])) == 0
    tree3.destroy(rt.Attributes())

    # Tear step 8 (delete its rng.json) -> "latest" falls back to step 4.
    os.remove(os.path.join(ckpt, "8", "rng.json"))
    runtime4 = Runtime(mesh_shape={"data": 8}, project_dir=str(tmp_path))
    model4 = MLP(in_features=8, num_classes=4, hidden=(16,))
    tree4, module4 = build(
        runtime4, model4, data, ckpt, num_epochs=2, resume_from="latest"
    )
    tree4.setup(rt.Attributes())
    assert int(np.asarray(module4.state["step"])) == 4
    tree4.destroy(rt.Attributes())


def test_async_writer_surfaces_errors_and_backpressures():
    import threading

    from rocket_tpu.runtime.checkpoint_io import AsyncWriter

    writer = AsyncWriter()
    order = []

    # Backpressure: submit() blocks until the in-flight write finishes —
    # the second submit cannot return while "a" is still gated.
    gate = threading.Event()

    def slow_a():
        gate.wait(5.0)
        order.append("a")

    import time

    writer.submit(slow_a)
    release = threading.Timer(0.2, gate.set)
    release.start()
    t0 = time.perf_counter()
    writer.submit(lambda: order.append("b"))  # must block until "a" ran
    blocked_for = time.perf_counter() - t0
    assert blocked_for >= 0.15, blocked_for  # submit #2 waited on the gate
    assert order[0] == "a"
    writer.wait()
    assert order == ["a", "b"]

    def boom():
        raise OSError("disk full")

    writer.submit(boom)
    with pytest.raises(RuntimeError, match="async checkpoint write failed"):
        writer.wait()
    # The error is consumed; the writer is reusable afterwards.
    writer.submit(lambda: order.append("c"))
    writer.wait()
    assert order[-1] == "c"


def test_overwrite_false_refuses_existing_step(tmp_path):
    """Reference parity (checkpoint.py:66-69): overwrite=False raises
    rather than clobbering an existing step directory."""
    runtime = Runtime(mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path))
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    ckpt = rt.Checkpointer(
        output_dir=str(tmp_path / "ck"), save_every=1, overwrite=False
    )
    launcher = rt.Launcher(
        [rt.Looper([rt.Dataset(make_dataset(n=32), batch_size=32), module, ckpt],
                   tag="train")],
        num_epochs=1, statefull=True, runtime=runtime,
    )
    launcher.launch()  # writes step 1
    os.makedirs(str(tmp_path / "ck" / "2"))  # simulate a pre-existing target
    with pytest.raises(RuntimeError, match="overwrite"):
        ckpt.save(step=2)


def test_enabling_ema_mid_run_resumes_from_pre_ema_checkpoint(tmp_path):
    """A checkpoint saved WITHOUT EMA restores into a tree that now has
    ema_decay: params restore normally and the EMA shadow seeds from the
    checkpoint's params (not the fresh init)."""
    runtime = Runtime(mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path))
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    data = make_dataset(n=64)
    launcher, module = build(runtime, model, data, str(tmp_path / "ck"),
                             num_epochs=1, save_every=2)
    launcher.launch()  # saves step 2 without EMA

    runtime2 = Runtime(mesh_shape={"data": 8}, seed=1, project_dir=str(tmp_path))
    model2 = MLP(in_features=8, num_classes=4, hidden=(16,))
    module2 = rt.Module(
        model2,
        capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)],
        ema_decay=0.99,
    )
    tree2 = rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=32),
                    module2,
                    rt.Checkpointer(output_dir=str(tmp_path / "ck"),
                                    resume_from=str(tmp_path / "ck" / "2")),
                ],
                tag="train",
            )
        ],
        num_epochs=1, statefull=True, runtime=runtime2,
    )
    tree2.setup(rt.Attributes())
    # EMA seeded from the RESTORED params, not the fresh (seed=1) init.
    import jax

    for e, p in zip(jax.tree.leaves(module2.state["ema_params"]),
                    jax.tree.leaves(module2.state["params"])):
        np.testing.assert_array_equal(np.asarray(e), np.asarray(p))
