"""Fused decode-attention kernel (ops/decode_attention.py) vs the einsum
path, interpret mode — same kernel code the TPU compiles."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocket_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_supported,
)


def _reference(q, kn, vn, kc, vc, pos):
    b, hq, d = q.shape
    h_kv, t = kc.shape[1], kc.shape[2]
    g = hq // h_kv
    kc = kc.at[:, :, pos, :].set(kn)
    vc = vc.at[:, :, pos, :].set(vn)
    q5 = q.reshape(b, h_kv, g, d).astype(jnp.float32)
    s = jnp.einsum("bkgd,bkmd->bkgm", q5, kc.astype(jnp.float32)) / np.sqrt(d)
    s = jnp.where((jnp.arange(t) <= pos)[None, None, None, :], s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    o = jnp.einsum("bkgm,bkmd->bkgd", p, vc.astype(jnp.float32))
    return o.reshape(b, hq, d), kc, vc


@pytest.mark.parametrize("hq,h_kv", [(4, 4), (6, 2), (4, 1)])
@pytest.mark.parametrize("pos", [0, 7, 8, 37, 127])  # incl. tile edges
def test_matches_einsum_reference(hq, h_kv, pos):
    b, t, d = 2, 128, 16
    rng = np.random.default_rng(pos)
    q = jnp.asarray(rng.normal(size=(b, hq, d)), jnp.float32)
    kn = jnp.asarray(rng.normal(size=(b, h_kv, d)), jnp.float32)
    vn = jnp.asarray(rng.normal(size=(b, h_kv, d)), jnp.float32)
    kc = jnp.asarray(rng.normal(size=(b, h_kv, t, d)), jnp.float32)
    vc = jnp.asarray(rng.normal(size=(b, h_kv, t, d)), jnp.float32)
    out, ko, vo = decode_attention(q, kn, vn, kc, vc, pos, interpret=True)
    ref_o, ref_k, ref_v = _reference(q, kn, vn, kc, vc, pos)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_o), atol=2e-5)
    np.testing.assert_array_equal(np.asarray(ko), np.asarray(ref_k))
    np.testing.assert_array_equal(np.asarray(vo), np.asarray(ref_v))


def test_bf16_and_validation():
    b, hq, h_kv, t, d = 1, 4, 2, 128, 16
    rng = np.random.default_rng(0)
    args = [
        jnp.asarray(rng.normal(size=shape), jnp.bfloat16)
        for shape in [
            (b, hq, d), (b, h_kv, d), (b, h_kv, d),
            (b, h_kv, t, d), (b, h_kv, t, d),
        ]
    ]
    out, _, _ = decode_attention(*args, 5, interpret=True)
    ref_o, _, _ = _reference(*args, 5)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref_o), atol=2e-2
    )

    assert not decode_attention_supported(100, 16)  # T not 128-multiple
    assert decode_attention_supported(256, 64)
    # Long-context Llama-style cache blocks exceed the VMEM budget.
    assert not decode_attention_supported(8192, 128, h_kv=8, itemsize=2)
    with pytest.raises(ValueError, match="multiple"):
        decode_attention(
            args[0], args[1], args[2],
            jnp.zeros((b, h_kv, 100, d), jnp.bfloat16),
            jnp.zeros((b, h_kv, 100, d), jnp.bfloat16),
            3, interpret=True,
        )
    with pytest.raises(ValueError, match="Hq"):
        decode_attention(
            jnp.zeros((b, 3, d), jnp.bfloat16), args[1], args[2],
            args[3], args[4], 3, interpret=True,
        )


def test_apply_cached_kernel_path_matches_einsum(monkeypatch):
    """MultiHeadAttention.apply_cached through the fused kernel (forced on
    CPU via interpret) must equal the einsum path bit-for-tolerance."""
    from rocket_tpu.nn.attention import MultiHeadAttention

    mha = MultiHeadAttention(32, num_heads=4, num_kv_heads=2, rope=True)
    params = mha.init_params(jax.random.key(0))
    cache = mha.init_cache(2, 128)
    x = jax.random.normal(jax.random.key(1), (2, 1, 32))

    out_ref, cache_ref = mha.apply_cached(params, x, cache, 9)

    monkeypatch.setattr(
        MultiHeadAttention, "_use_decode_kernel",
        lambda self, t, itemsize: True,
    )
    import rocket_tpu.ops.decode_attention as da

    orig = da.decode_attention
    monkeypatch.setattr(
        da, "decode_attention",
        lambda *a, **kw: orig(*a, **dict(kw, interpret=True)),
    )
    out_k, cache_k = mha.apply_cached(params, x, cache, 9)
    np.testing.assert_allclose(
        np.asarray(out_k), np.asarray(out_ref), atol=2e-5
    )
    np.testing.assert_allclose(
        np.asarray(cache_k["k"]), np.asarray(cache_ref["k"]), atol=2e-6
    )
