import pickle

from rocket_tpu import Attributes


def test_missing_key_reads_none():
    attrs = Attributes()
    assert attrs.batch is None
    assert attrs["batch"] is None


def test_set_get_del():
    attrs = Attributes()
    attrs.batch = [1, 2]
    assert attrs.batch == [1, 2]
    assert attrs["batch"] == [1, 2]
    del attrs.batch
    assert attrs.batch is None
    del attrs.batch  # deleting a missing key is a no-op


def test_nested_chained_access():
    attrs = Attributes()
    attrs.looper = {"state": {"loss": 1.5}}
    assert attrs.looper.state.loss == 1.5
    attrs.looper.state.loss = 2.0
    assert attrs["looper"]["state"]["loss"] == 2.0


def test_is_a_dict():
    attrs = Attributes(a=1)
    assert isinstance(attrs, dict)
    assert dict(attrs) == {"a": 1}


def test_flat_items():
    attrs = Attributes(a=1, b=Attributes(c=2, d=Attributes(e=3)))
    flat = dict(attrs.flat_items())
    assert flat == {"a": 1, "b.c": 2, "b.d.e": 3}


def test_copy_independent():
    attrs = Attributes(a=1)
    clone = attrs.copy()
    clone.a = 2
    assert attrs.a == 1


def test_pickle_roundtrip():
    attrs = Attributes(a=1, b={"c": 2})
    restored = pickle.loads(pickle.dumps(attrs))
    assert restored.a == 1
    assert restored.b.c == 2
