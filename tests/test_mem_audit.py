"""mem_audit: the HBM liveness simulation and the RKT80x rules.

The liveness model is pinned on a hand-written scheduled HLO module
whose peak, donation aliasing and carried-across-peak set are computed
by hand; the rule check functions are exercised as pure functions; one
end-to-end audit AOT-compiles a tiny donated train step and must come
back clean with a tight liveness-vs-``memory_analysis()``
reconciliation. The five real targets' numbers are gated by the
committed budgets (tests/test_analysis_cli.py and scripts/check.sh).
"""

import jax
import jax.numpy as jnp

from rocket_tpu.analysis.mem_audit import (
    MEM_TARGETS,
    _parse_io_alias,
    audit_memory,
    simulate_liveness,
)
from rocket_tpu.analysis.rules.mem_rules import (
    MEM_RULES,
    check_donation_coverage,
    check_oom_frontier,
    check_reconciliation,
    check_remat_effectiveness,
)
from rocket_tpu.analysis.sched_audit import parse_hlo_module

B = 256 * 256 * 4  # one f32[256,256] buffer

# Hand-scheduled module: p0 donated into output {0}; `a` is the one
# buffer carried across the 3-buffer peak (a+b+c live during %c).
HLO = """\
HloModule jit_step, is_scheduled=true, input_output_alias={ {0}: (0, {}, may-alias) }

ENTRY %main (p0: f32[256,256], p1: f32[256,256]) -> (f32[256,256], f32[]) {
  %p0 = f32[256,256]{1,0} parameter(0)
  %p1 = f32[256,256]{1,0} parameter(1)
  %a = f32[256,256]{1,0} dot(f32[256,256]{1,0} %p0, f32[256,256]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %b = f32[256,256]{1,0} exponential(f32[256,256]{1,0} %a)
  %c = f32[256,256]{1,0} negate(f32[256,256]{1,0} %b)
  %d = f32[256,256]{1,0} add(f32[256,256]{1,0} %a, f32[256,256]{1,0} %c)
  %k = f32[] constant(0)
  ROOT %t = (f32[256,256]{1,0}, f32[]) tuple(f32[256,256]{1,0} %d, f32[] %k)
}
"""


def test_parse_io_alias_reads_donation_entries():
    assert _parse_io_alias(HLO) == {0: 0}
    assert _parse_io_alias("HloModule m, is_scheduled=true\n") == {}


def test_simulate_liveness_peak_donation_and_carried_set():
    entry, _ = parse_hlo_module(HLO)
    res = simulate_liveness(entry, HLO)
    # Arguments live the whole step; p0 is proven donated.
    assert res.argument_bytes == 2 * B
    assert res.donated_arg_bytes == B
    assert res.undonated_arg_bytes == B
    # Peak: a+b+c live while %c computes. The donated output %d writes
    # into p0's buffer, so it adds nothing.
    assert res.peak_temp_bytes == 3 * B
    assert res.peak_bytes == 2 * B + 3 * B
    # `a` (born at %a, last consumed at %d) is the only buffer carried
    # across the peak — the saved-for-backward analogue.
    assert res.saved_activation_bytes == B
    bd = res.peak_breakdown
    assert bd["state"] == B and bd["batch"] == B
    assert bd["saved_activations"] == B and bd["temps"] == 2 * B
    assert sum(bd.values()) == res.peak_bytes


def test_mem_rules_catalog_ids():
    assert [r[0] for r in MEM_RULES] == [
        "RKT801", "RKT802", "RKT803", "RKT804", "RKT805",
    ]


def test_check_donation_coverage_fires_and_skips():
    bad = check_donation_coverage(0, 1 << 20, label="t")
    assert [f.rule for f in bad] == ["RKT801"]
    ok = check_donation_coverage(1 << 20, 1 << 20, label="t")
    assert ok == []
    # Eval transforms declare expects_donation=False: never fires.
    assert check_donation_coverage(
        0, 1 << 20, expects_donation=False, label="t"
    ) == []


def test_check_remat_effectiveness_zero_ceiling_disables():
    assert check_remat_effectiveness(1 << 30, 0, label="t") == []
    assert [f.rule for f in check_remat_effectiveness(
        2 << 20, 1 << 20, label="t"
    )] == ["RKT802"]


def test_check_oom_frontier_reports_max_batch():
    frontier = {"TPU v5 lite": 7}
    bad = check_oom_frontier(
        3 << 30, 1 << 30, frontier=frontier, batch_size=32, label="t"
    )
    assert [f.rule for f in bad] == ["RKT804"]
    assert "batch<=7" in bad[0].message
    assert check_oom_frontier(1 << 20, 1 << 30, label="t") == []


def test_check_reconciliation_floor():
    assert [f.rule for f in check_reconciliation(
        20 << 20, 10 << 20, floor=0.5, label="t"
    )] == ["RKT805"]
    assert check_reconciliation(11 << 20, 10 << 20, floor=0.5,
                                label="t") == []
    # No XLA reference -> nothing to reconcile against.
    assert check_reconciliation(1 << 20, None, label="t") == []


def test_audit_memory_clean_on_tiny_donated_step():
    """End to end on a real AOT compile: a fully donated SGD step must
    pass every rule and reconcile tightly with XLA's own analysis."""
    variables = {
        "params": {"w": jax.ShapeDtypeStruct((64, 64), jnp.float32)},
        "state": {},
    }
    batch = jax.ShapeDtypeStruct((8, 64), jnp.float32)

    def step(variables, batch):
        def loss_fn(params):
            h = jnp.tanh(batch @ params["w"])
            return (h * h).mean()

        loss, grads = jax.value_and_grad(loss_fn)(variables["params"])
        new = jax.tree.map(lambda p, g: p - 0.1 * g,
                           variables["params"], grads)
        return {"params": new, "state": variables["state"]}, loss

    report = audit_memory(
        step, variables, batch, mesh_shape={"data": 1},
        donate_argnums=(0,), label="unit",
    )
    assert report.clean, [f.render() for f in report.findings]
    rec = report.record
    assert rec["donated_bytes"] == 64 * 64 * 4
    assert rec["predicted_peak_bytes"] > 0
    assert rec["reconciliation_error"] is not None
    assert rec["reconciliation_error"] < 0.25
    assert rec["oom_frontier"]  # every known device kind gets a bound


def test_mem_targets_cover_the_train_matrix():
    names = set(MEM_TARGETS)
    assert {"tp_1x8", "tp_2x4", "tp_2x4_eval", "fsdp_1x8",
            "dp_resnet_1x8", "badmem"} <= names
    assert MEM_TARGETS["badmem"].demo
    assert not MEM_TARGETS["tp_2x4_eval"].expects_donation
