"""Optimizer factories: decay masking, schedule injection."""

import jax
import jax.numpy as jnp
import numpy as np

from rocket_tpu import optim


def test_adamw_masks_decay_off_1d_params():
    """With zero grads, decay is the only force: 2-D kernels shrink, 1-D
    biases/scales stay put (GPT-2 convention); mask_1d=False decays both."""
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    grads = jax.tree.map(jnp.zeros_like, params)

    def step(factory):
        tx = optim.resolve(factory, 0.1)
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        import optax

        return optax.apply_updates(params, updates)

    masked = step(optim.adamw(weight_decay=0.1))
    assert float(jnp.max(jnp.abs(masked["b"] - 1.0))) == 0.0  # exempt
    assert float(masked["w"][0, 0]) < 1.0  # decayed

    decay_all = step(optim.adamw(weight_decay=0.1, mask_1d=False))
    assert float(decay_all["b"][0]) < 1.0


def test_adamw_zero_decay_needs_no_mask():
    params = {"b": jnp.ones((4,))}
    tx = optim.resolve(optim.adamw(weight_decay=0.0), 0.1)
    state = tx.init(params)
    updates, _ = tx.update(jax.tree.map(jnp.zeros_like, params), state, params)
    np.testing.assert_array_equal(np.asarray(updates["b"]), 0.0)


def test_lion_trains_and_masks_decay():
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    tx = optim.resolve(optim.lion(weight_decay=0.5), 0.1)
    state = tx.init(params)
    grads = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    import optax

    updates, _ = tx.update(grads, state, params)
    new = optax.apply_updates(params, updates)
    assert float(new["w"][0, 0]) < 1.0     # sign update + decay move w
    assert float(new["b"][0]) == 1.0       # zero grad + masked decay: untouched
