"""Optimizer factories: decay masking, schedule injection."""

import jax
import jax.numpy as jnp
import numpy as np

from rocket_tpu import optim


def test_adamw_masks_decay_off_1d_params():
    """With zero grads, decay is the only force: 2-D kernels shrink, 1-D
    biases/scales stay put (GPT-2 convention); mask_1d=False decays both."""
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    grads = jax.tree.map(jnp.zeros_like, params)

    def step(factory):
        tx = optim.resolve(factory, 0.1)
        state = tx.init(params)
        updates, _ = tx.update(grads, state, params)
        import optax

        return optax.apply_updates(params, updates)

    masked = step(optim.adamw(weight_decay=0.1))
    assert float(jnp.max(jnp.abs(masked["b"] - 1.0))) == 0.0  # exempt
    assert float(masked["w"][0, 0]) < 1.0  # decayed

    decay_all = step(optim.adamw(weight_decay=0.1, mask_1d=False))
    assert float(decay_all["b"][0]) < 1.0


def test_adamw_zero_decay_needs_no_mask():
    params = {"b": jnp.ones((4,))}
    tx = optim.resolve(optim.adamw(weight_decay=0.0), 0.1)
    state = tx.init(params)
    updates, _ = tx.update(jax.tree.map(jnp.zeros_like, params), state, params)
    np.testing.assert_array_equal(np.asarray(updates["b"]), 0.0)


def test_lion_trains_and_masks_decay():
    params = {"w": jnp.ones((4, 4)), "b": jnp.ones((4,))}
    tx = optim.resolve(optim.lion(weight_decay=0.5), 0.1)
    state = tx.init(params)
    grads = {"w": jnp.ones((4, 4)), "b": jnp.zeros((4,))}
    import optax

    updates, _ = tx.update(grads, state, params)
    new = optax.apply_updates(params, updates)
    assert float(new["w"][0, 0]) < 1.0     # sign update + decay move w
    assert float(new["b"][0]) == 1.0       # zero grad + masked decay: untouched


def test_linear_and_wsd_schedules():
    lin = optim.linear_lr(1.0, 10)
    assert float(lin(0)) == 1.0 and abs(float(lin(10))) < 1e-7
    assert abs(float(lin(5)) - 0.5) < 1e-6

    wsd = optim.warmup_stable_decay_lr(1.0, warmup_steps=10, total_steps=100,
                                       decay_steps=20)
    assert float(wsd(0)) == 0.0
    assert abs(float(wsd(10)) - 1.0) < 1e-6   # warmed up
    assert abs(float(wsd(50)) - 1.0) < 1e-6   # plateau
    assert abs(float(wsd(90)) - 0.5) < 1e-6   # mid-decay
    assert abs(float(wsd(100))) < 1e-6        # done

    import pytest

    with pytest.raises(ValueError, match="exceed total"):
        optim.warmup_stable_decay_lr(1.0, 60, 100, 60)
