"""rocket_tpu.obs: spans, goodput accounting, metrics registry, watchdog,
and the end-to-end telemetry files a run writes at DESTROY."""

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import optax
import pytest

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.obs import (
    Goodput,
    MetricsRegistry,
    SpanRecorder,
    Telemetry,
    Watchdog,
    load_chrome_trace,
)
from rocket_tpu.runtime.context import Runtime


# -- goodput ---------------------------------------------------------------


def test_goodput_exclusive_accounting_and_derived_other():
    g = Goodput()
    g.push("step", 0.0)
    g.push("data_wait", 2.0)   # pauses step at t=2
    g.pop(5.0)                 # data_wait = 3, step resumes
    g.pop(6.0)                 # step = 2 + 1
    totals = g.totals()
    assert totals["step"] == pytest.approx(3.0)
    assert totals["data_wait"] == pytest.approx(3.0)

    report = g.report(total_wall_s=10.0)
    assert report["categories"]["other"] == pytest.approx(4.0)
    assert sum(report["categories"].values()) == pytest.approx(
        report["total_wall_s"]
    )
    assert report["goodput_fraction"] == pytest.approx(0.3)


def test_goodput_total_never_below_measured():
    g = Goodput()
    g.push("step", 0.0)
    g.pop(2.0)
    report = g.report(total_wall_s=1.0)  # caller's clock lagged
    assert report["total_wall_s"] == pytest.approx(2.0)
    assert report["categories"]["other"] == 0.0


# -- registry --------------------------------------------------------------


def test_registry_instruments_and_snapshots():
    reg = MetricsRegistry()
    reg.counter("c").inc()
    reg.counter("c").inc(2)
    reg.gauge("g").set(7)
    hist = reg.histogram("h", base=1.0)
    for v in (0.5, 1.0, 3.0, 3.0):
        hist.observe(v)

    snap = reg.snapshot()
    assert snap["counters"]["c"] == 3.0
    assert snap["gauges"]["g"] == 7.0
    assert snap["histograms"]["h"]["count"] == 4
    assert snap["histograms"]["h"]["max"] == 3.0
    assert snap["histograms"]["h"]["mean"] == pytest.approx(1.875)
    # le_1 bucket holds the two <=1.0 observations, le_4 the two 3.0s.
    assert snap["histograms"]["h"]["buckets"] == {"le_1": 2, "le_4": 2}

    scalars = reg.scalars()
    assert scalars["c"] == 3.0 and scalars["g"] == 7.0
    assert scalars["h/count"] == 4.0
    assert scalars["h/mean"] == pytest.approx(1.875)


def test_registry_device_memory_is_harmless_on_cpu():
    reg = MetricsRegistry()
    reg.record_device_memory()  # CPU devices report no memory stats
    assert "hbm/bytes_in_use_max" not in reg.snapshot()["gauges"]


# -- spans -----------------------------------------------------------------


def test_span_recorder_chrome_trace_roundtrip(tmp_path):
    rec = SpanRecorder()
    rec.add("outer", "step", rec.t0, 0.5)
    rec.add("inner", None, rec.t0 + 0.1, 0.2)
    path = rec.write(str(tmp_path / "spans.trace.json"))
    events = load_chrome_trace(path)
    complete = [e for e in events if e["ph"] == "X"]
    assert {e["name"] for e in complete} == {"outer", "inner"}
    outer = next(e for e in complete if e["name"] == "outer")
    assert outer["cat"] == "step" and outer["dur"] == pytest.approx(5e5)
    assert outer["ts"] == pytest.approx(0.0, abs=1e-3)


def test_span_recorder_bounded_buffer():
    rec = SpanRecorder(max_events=2)
    for i in range(5):
        rec.add(f"s{i}", None, 0.0, 0.1)
    assert len(rec) == 2 and rec.dropped == 3
    assert rec.to_chrome_trace()["otherData"]["dropped"] == 3


def test_telemetry_span_tracks_open_stack_and_goodput():
    tel = Telemetry(enabled=True)
    with tel.span("phase", cat="step"):
        with tel.span("inner"):
            stacks = tel.spans.open_spans()
            names = stacks[threading.get_ident()]
            assert names == ["phase", "inner"]
    assert tel.spans.open_spans() == {}
    assert tel.goodput.totals()["step"] > 0.0
    assert len(tel.spans) == 2


def test_disabled_telemetry_is_inert(tmp_path):
    tel = Telemetry(enabled=False)
    with tel.span("x", cat="step"):
        pass
    assert len(tel.spans) == 0
    assert tel.scalars_snapshot() == {}
    assert tel.flush(str(tmp_path)) is None
    assert not os.path.exists(tmp_path / "telemetry.json")


# -- watchdog --------------------------------------------------------------


def test_watchdog_fires_on_stall_and_dumps_stacks():
    reports = []
    rec = SpanRecorder()
    reg = MetricsRegistry()
    dog = Watchdog(0.15, on_stall=reports.append, spans=rec, registry=reg,
                   poll_s=0.02)
    dog.start()
    try:
        dog.arm()
        rec.push_open("train/step", "step", time.perf_counter())
        deadline = time.time() + 5.0
        while not reports and time.time() < deadline:
            time.sleep(0.02)
    finally:
        rec.pop_open()
        dog.stop()
    assert reports, "watchdog never fired on a stalled heartbeat"
    report = reports[0]
    assert "no step completed" in report
    assert "train/step" in report            # the open span stack
    assert "MainThread" in report            # thread stacks
    assert "live jax arrays" in report
    assert dog.stall_count >= 1
    assert reg.snapshot()["counters"]["watchdog/stalls"] >= 1


def test_watchdog_does_not_fire_while_beating():
    reports = []
    dog = Watchdog(0.2, on_stall=reports.append, poll_s=0.02)
    dog.start()
    try:
        dog.arm()
        for _ in range(10):
            time.sleep(0.05)
            dog.beat()
        dog.disarm()
        time.sleep(0.3)  # disarmed: a silent heartbeat must not fire
    finally:
        dog.stop()
    assert reports == []


def test_explicit_watchdog_secs_implies_telemetry(tmp_path):
    """An explicit ask for hang protection must never silently no-op:
    watchdog_secs= with telemetry unset turns the subsystem on."""
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        watchdog_secs=30.0,
    )
    try:
        assert runtime.telemetry.enabled
        assert runtime.telemetry.watchdog is not None
        assert runtime.telemetry.watchdog.deadline_s == 30.0
    finally:
        runtime.end_training()


def test_watchdog_fires_on_artificially_stalled_step(tmp_path):
    """Acceptance: a Looper step that hangs past the deadline produces a
    stall dump while the run is still going."""
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        telemetry=True, watchdog_secs=0.2,
    )
    runtime.telemetry.watchdog._poll_s = 0.02  # fast test cadence

    class Stall(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)
            self.stalled = False

        def launch(self, attrs=None):
            if not self.stalled:
                self.stalled = True
                deadline = time.time() + 5.0
                dog = self._runtime.telemetry.watchdog
                while dog.stall_count == 0 and time.time() < deadline:
                    time.sleep(0.02)

    data = [{"x": np.float32(i)} for i in range(16)]
    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=8, fuse_gather=False),
                    Stall()], tag="train", progress=False)],
        num_epochs=1, runtime=runtime,
    ).launch()
    telemetry_doc = json.load(
        open(tmp_path / "runs" / "telemetry" / "telemetry.json")
    )
    assert telemetry_doc["watchdog"]["stalls"] >= 1
    assert telemetry_doc["watchdog"]["report_file"] == "watchdog_stalls.txt"
    dump = (tmp_path / "runs" / "telemetry" / "watchdog_stalls.txt").read_text()
    assert "no step completed" in dump
    # The main thread's stack shows the stalled capsule's launch frame,
    # and the open-span stack names the wave it was inside.
    assert "launch" in dump
    assert "train/step" in dump


# -- end-to-end ------------------------------------------------------------


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def _train_tree(runtime, runs_dir, data, num_epochs=2):
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    return rt.Launcher(
        [rt.Looper(
            [rt.Dataset(data, batch_size=32), module, rt.Profiler(),
             rt.Tracker(project="obs_e2e", directory=runs_dir)],
            tag="train", progress=False,
        )],
        num_epochs=num_epochs, runtime=runtime,
    )


def _dataset(n=128):
    rng = np.random.default_rng(0)
    return [
        {"image": rng.normal(size=8).astype(np.float32),
         "label": np.int32(i % 4)}
        for i in range(n)
    ]


def test_run_writes_telemetry_and_spans_with_strict_guards(tmp_path):
    """The acceptance-criteria run: telemetry + strict mode together.
    telemetry.json parses, goodput sums to wall-clock within 5%, the span
    file is valid Chrome-trace JSON with the expected categories, and the
    obs/* scalars landed in the tracker stream."""
    runs_dir = str(tmp_path / "runs")
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        strict=True, telemetry=True,
    )
    _train_tree(runtime, runs_dir, _dataset()).launch()

    out_dir = tmp_path / "runs" / "obs_e2e"
    record = json.load(open(out_dir / "telemetry.json"))
    goodput = record["goodput"]
    assert goodput["total_wall_s"] > 0
    assert sum(goodput["categories"].values()) == pytest.approx(
        goodput["total_wall_s"], rel=0.05
    )
    assert goodput["categories"]["step"] > 0
    assert goodput["categories"]["compile"] > 0
    assert record["metrics"]["counters"]["compile/events"] > 0
    # StrictMode's retrace count mirrored into the registry.
    assert any(
        k.startswith("strict/retraces/train_step")
        for k in record["metrics"]["gauges"]
    )

    events = load_chrome_trace(str(out_dir / "spans.trace.json"))
    complete = [e for e in events if e.get("ph") == "X"]
    cats = {e["cat"] for e in complete}
    assert {"step", "compile", "data_wait", "flush"} <= cats
    # Dispatch spans from the Capsule.dispatch choke point.
    assert any(e["name"] == "Dataset.launch" for e in complete)
    assert any(e["name"].startswith("compile/train_step") for e in complete)

    with open(os.path.join(runs_dir, "obs_e2e.jsonl")) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    obs_keys = {k for rec in lines for k in rec if k.startswith("obs/")}
    assert "obs/goodput/step_fraction" in obs_keys
    assert "obs/perf/steps_per_sec" in obs_keys


def test_telemetry_disabled_writes_nothing(tmp_path):
    runs_dir = str(tmp_path / "runs")
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
    )
    _train_tree(runtime, runs_dir, _dataset(64), num_epochs=1).launch()
    assert not (tmp_path / "runs" / "obs_e2e" / "telemetry.json").exists()


def test_prefetch_records_queue_depth(tmp_path):
    from rocket_tpu.data.prefetch import PrefetchIterator

    tel = Telemetry(enabled=True)
    it = PrefetchIterator(iter(range(8)), depth=2, telemetry=tel)
    assert list(it) == list(range(8))
    hist = tel.registry.snapshot()["histograms"]["data/prefetch_depth"]
    assert hist["count"] >= 8  # one observation per dequeue (incl. DONE)
    # Worker-side produce spans on the prefetch thread's trace line.
    assert any(
        name == "data/prefetch_produce" for name, *_ in tel.spans.events()
    )


def test_loader_counts_produced_batches():
    from rocket_tpu.data.loader import DataLoader

    tel = Telemetry(enabled=True)
    data = [{"x": np.float32(i)} for i in range(64)]
    loader = DataLoader(data, batch_size=16, telemetry=tel)
    assert len(list(loader)) == 4
    counters = tel.registry.snapshot()["counters"]
    assert counters["data/batches_produced"] == 4.0
    assert "data/worker_batches" not in counters  # serial path


def test_tracker_backend_closed_by_runtime_teardown(tmp_path):
    """Satellite regression: JsonlBackend file handles must not leak past
    DESTROY — Launcher teardown (Runtime.end_training) closes every
    registered backend even when one of them throws."""
    runs_dir = str(tmp_path / "runs")
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
    )
    tracker = rt.Tracker(project="obs_e2e", directory=runs_dir)
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    backend_seen = {}

    class Grab(rt.Capsule):
        def __init__(self):
            super().__init__(priority=10)

        def launch(self, attrs=None):
            backend_seen["backend"] = runtime.get_tracker("jsonl")

    launcher = rt.Launcher(
        [rt.Looper(
            [rt.Dataset(_dataset(64), batch_size=32), module, tracker,
             Grab()],
            tag="train", progress=False,
        )],
        num_epochs=1, runtime=runtime,
    )
    launcher.launch()
    backend = backend_seen["backend"]
    assert backend is not None
    assert backend._file.closed, "JsonlBackend handle leaked past teardown"
    assert runtime.trackers == {}
    # The capsule dropped its own reference at DESTROY too.
    assert tracker._backend is None


def test_end_training_survives_a_failing_backend_close(tmp_path):
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
    )

    closed = []

    class Bad:
        def close(self):
            raise RuntimeError("socket gone")

    class Good:
        def close(self):
            closed.append(True)

    runtime.init_tracker("bad", Bad())
    runtime.init_tracker("good", Good())
    runtime.end_training()  # must not raise
    assert closed == [True]
    assert runtime.trackers == {}


def test_report_cli_renders_telemetry_and_span_files(tmp_path):
    runs_dir = str(tmp_path / "runs")
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        telemetry=True,
    )
    _train_tree(runtime, runs_dir, _dataset(64), num_epochs=1).launch()
    out_dir = tmp_path / "runs" / "obs_e2e"
    for name, expect in (
        ("telemetry.json", "goodput (step fraction)"),
        ("spans.trace.json", "span file:"),
    ):
        proc = subprocess.run(
            [sys.executable, "-m", "rocket_tpu.obs", "report",
             str(out_dir / name)],
            capture_output=True, text=True,
        )
        assert proc.returncode == 0, proc.stderr
        assert expect in proc.stdout

    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "report",
         str(tmp_path / "missing.json")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 2


def test_report_cli_zero_step_run_renders_no_steps_row(tmp_path):
    """Satellite regression: a telemetry.json from a zero-step run (no
    fractions block, zero wall-clock) must render an explicit "no steps
    recorded" row — never crash on the degenerate goodput record."""
    zero = {
        "version": 1,
        "goodput": {
            "total_wall_s": 0.0,
            "categories": {cat: 0.0 for cat in
                           ("compile", "data_wait", "step", "checkpoint",
                            "flush", "other")},
            # No "fractions" key: the CLI must derive them with a guarded
            # division (total == 0 was the ZeroDivision hazard).
        },
        "metrics": {"counters": {}, "gauges": {}, "histograms": {}},
        "spans": {"file": "spans.trace.json", "events": 0, "dropped": 0},
        "watchdog": {"enabled": False, "deadline_s": None, "stalls": 0},
    }
    path = tmp_path / "telemetry.json"
    path.write_text(json.dumps(zero))
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "report", str(path)],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "no steps recorded" in proc.stdout
    assert "ZeroDivisionError" not in proc.stderr

    # A freshly constructed (zero-step) Telemetry's own flush renders too.
    tel = Telemetry(enabled=True)
    out = tel.flush(str(tmp_path / "fresh"))
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "report",
         os.path.join(out, "telemetry.json")],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "no steps recorded" in proc.stdout


def test_span_drops_surface_as_metric_and_teardown_warning(tmp_path, caplog):
    """Satellite: SpanRecorder drops become an obs/spans_dropped registry
    metric and a one-line teardown warning, so a truncated trace is never
    mistaken for a complete one."""
    import logging

    logger = logging.getLogger("rocket_tpu.test_obs_drops")
    tel = Telemetry(enabled=True, max_span_events=2, logger=logger)
    for i in range(5):
        with tel.span(f"s{i}", cat="step"):
            pass
    assert tel.spans.dropped == 3
    assert tel.scalars_snapshot()["obs/spans_dropped"] == 3.0
    assert tel.summary()["metrics"]["gauges"]["obs/spans_dropped"] == 3.0
    with caplog.at_level("WARNING", logger=logger.name):
        tel.close(str(tmp_path), write=False)
    assert any("span(s) dropped" in rec.message for rec in caplog.records)

    # A clean run stays quiet.
    tel2 = Telemetry(enabled=True, logger=logger)
    with tel2.span("ok", cat="step"):
        pass
    caplog.clear()
    with caplog.at_level("WARNING", logger=logger.name):
        tel2.close(str(tmp_path), write=False)
    assert not any("dropped" in rec.message for rec in caplog.records)
