"""The bench stdout line must fit the driver's 2,000-byte tail capture.

BENCH_r04.json came back ``parsed: null`` because the monolithic line
(headline + full per-config ``extra``) outgrew the capture window. The
round-5 contract: ``bench.format_line`` emits a compact self-contained
headline ≤ ``bench.MAX_LINE_BYTES`` (1,500 < 2,000 with headroom) no
matter how many configs exist or fail, and ``bench.write_detail`` carries
the full record to BENCH_DETAIL.json. These tests feed worst-case inputs
through the real emission path so adding a config can never silently
re-break the artifact.
"""

import json
import os
import sys
from pathlib import Path

import pytest

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import bench  # noqa: E402


def _full_result(name, rounds=8):
    """A maximal per-config result: every field populated, long history."""
    return {
        "metric": bench.METRIC_NAMES.get(
            name, f"{name}_tok_per_sec_per_chip"
        ),
        "value": 1234567.8,
        "unit": "tok/sec/chip",
        "vs_baseline": 12.345,
        "mfu": 0.5678,
        "best_value": 1345678.9,
        "best_mfu": 0.6123,
        "history": {f"r{i:02d}": 1234567.8 + i for i in range(1, rounds)}
        | {"now": 1234567.8},
    }


def _worst_case_results(n_extra=20):
    """Every real config fully populated, plus n_extra future configs —
    far beyond any plausible growth of BENCHES."""
    results = {name: _full_result(name) for name in bench.BENCHES}
    for i in range(n_extra):
        results[f"future_config_with_a_long_name_{i:02d}"] = _full_result(
            f"future_config_with_a_long_name_{i:02d}"
        )
    return results


def test_line_fits_capture_worst_case():
    line = bench.format_line(_worst_case_results())
    assert len(line) <= bench.MAX_LINE_BYTES
    parsed = json.loads(line)
    # The headline must survive every degradation step.
    assert parsed["metric"] == bench.METRIC_NAMES["gpt2"]
    assert parsed["value"] == 1234567.8
    assert parsed["mfu"] == 0.5678
    assert parsed["detail"] == "BENCH_DETAIL.json"


def test_line_fits_when_everything_errors():
    """str(exc) from an XLA failure routinely runs kilobytes — the line
    must fit even when every config carries an unbounded error string."""
    results = {
        name: {"metric": bench.METRIC_NAMES[name],
               "error": "XlaRuntimeError: " + "x" * 8000}
        for name in bench.BENCHES
    }
    line = bench.format_line(results)
    assert len(line) <= bench.MAX_LINE_BYTES
    parsed = json.loads(line)
    assert parsed["error"].startswith("XlaRuntimeError")


def test_normal_sweep_keeps_summary_and_history():
    """At today's config count nothing should be degraded away: the line
    carries the headline history AND one value per other config."""
    results = {name: _full_result(name) for name in bench.BENCHES}
    line = bench.format_line(results)
    assert len(line) <= bench.MAX_LINE_BYTES
    parsed = json.loads(line)
    assert "history" in parsed
    others = parsed["others"]
    for name in bench.BENCHES:
        if name == "gpt2":
            continue
        assert others[name] == 1234567.8
    assert others["resnet50_mfu"] == 0.568


def test_write_detail_round_trips(tmp_path):
    results = {name: _full_result(name) for name in bench.BENCHES}
    path = tmp_path / "BENCH_DETAIL.json"
    bench.write_detail(results, path=str(path))
    detail = json.loads(path.read_text())
    assert detail["headline_metric"] == bench.METRIC_NAMES["gpt2"]
    assert set(detail["configs"]) == set(bench.BENCHES)
    # Full fidelity: the detail file keeps what the line drops.
    assert detail["configs"]["llama"]["history"]["r01"] == 1234568.8


def test_write_detail_merges_partial_runs(tmp_path):
    """A --config X debugging run must not clobber the full-sweep record
    the stdout 'detail' pointer references."""
    path = tmp_path / "BENCH_DETAIL.json"
    full = {name: _full_result(name) for name in bench.BENCHES}
    bench.write_detail(full, path=str(path))
    partial = {"gpt2": dict(_full_result("gpt2"), value=999.9)}
    bench.write_detail(partial, path=str(path))
    detail = json.loads(path.read_text())
    assert set(detail["configs"]) == set(bench.BENCHES)
    assert detail["configs"]["gpt2"]["value"] == 999.9
    assert detail["configs"]["llama"]["value"] == 1234567.8


def test_write_detail_errored_rerun_keeps_good_record(tmp_path):
    """An errored re-run (debug OOM, transient XLA failure) must not
    destroy a committed good config record — it is annotated instead."""
    path = tmp_path / "BENCH_DETAIL.json"
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path))
    bench.write_detail(
        {"gpt2": {"metric": bench.METRIC_NAMES["gpt2"], "error": "OOM" * 200}},
        path=str(path),
    )
    rec = json.loads(path.read_text())["configs"]["gpt2"]
    assert rec["value"] == 1234567.8          # good record survives
    assert rec["last_error"].startswith("OOM")
    assert len(rec["last_error"]) <= 200
    # A fresh error with NO prior good record still lands as-is.
    bench.write_detail({"moe": {"metric": "m", "error": "boom"}},
                       path=str(path))
    assert json.loads(path.read_text())["configs"]["moe"]["error"] == "boom"
    # And a later good run replaces the annotated record cleanly.
    bench.write_detail({"gpt2": dict(_full_result("gpt2"), value=42.0)},
                       path=str(path))
    rec = json.loads(path.read_text())["configs"]["gpt2"]
    assert rec["value"] == 42.0 and "last_error" not in rec


def test_write_detail_carries_audit_calibration_across_partial_runs(
        tmp_path):
    """A partial run cannot recompute calibration entries (each needs
    that config's measured value from THIS run) — the committed blocks
    must survive, per-config for sched and whole for serve."""
    path = tmp_path / "BENCH_DETAIL.json"
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path))
    detail = json.loads(path.read_text())
    sched_cal = {"charlm": {"calibration_error": 1.5, "priced_for": "x"},
                 "resnet18": {"calibration_error": 0.9}}
    serve_cal = {"itl_calibration_error": -0.5, "predicted_itl_us": 10.0}
    detail.setdefault("sched_audit", {})["calibration"] = sched_cal
    detail.setdefault("serve_audit", {})["calibration"] = serve_cal
    path.write_text(json.dumps(detail))
    # A run that measured NO calibration config keeps both blocks whole.
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path))
    detail = json.loads(path.read_text())
    assert detail["sched_audit"]["calibration"] == sched_cal
    assert detail["serve_audit"]["calibration"] == serve_cal


def test_carry_calibration_merges_per_config_and_replaces_flat():
    # Per-config (sched): a fresh entry wins, missing configs carry.
    section = {"calibration": {"charlm": {"calibration_error": 2.0}}}
    bench._carry_calibration(section, {"calibration": {
        "charlm": {"calibration_error": 1.0},
        "resnet18": {"calibration_error": 0.5},
    }})
    assert section["calibration"]["charlm"]["calibration_error"] == 2.0
    assert section["calibration"]["resnet18"]["calibration_error"] == 0.5
    # Flat single-entry (serve): a fresh block replaces wholesale —
    # stale scalar keys from the prior run must not bleed in.
    section = {"calibration": {"itl_calibration_error": 0.1}}
    bench._carry_calibration(section, {"calibration": {
        "itl_calibration_error": 0.9, "ttft_calibration_error": 0.8,
    }})
    assert section["calibration"] == {"itl_calibration_error": 0.1}


def test_write_detail_survives_corrupt_prior(tmp_path):
    path = tmp_path / "BENCH_DETAIL.json"
    for corrupt in ("{not json", "[1,2]", '"a string"', ""):
        path.write_text(corrupt)
        bench.write_detail({"mlp": _full_result("mlp")}, path=str(path))
        assert "mlp" in json.loads(path.read_text())["configs"]


def test_write_detail_carries_shard_audit_record(tmp_path):
    """BENCH_DETAIL.json carries the statically-audited per-device HBM
    estimate and per-step collective-bytes totals (from the committed
    SPMD budget records the shard-audit CI gate verifies)."""
    path = tmp_path / "BENCH_DETAIL.json"
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path))
    audit = json.loads(path.read_text())["shard_audit"]
    assert audit["hbm_per_device_bytes"] > 0
    assert audit["collective_bytes_per_step"] > 0
    assert audit["source"] == "tests/fixtures/budgets"
    # Per-target breakdown: every committed budget shows up.
    assert "tp_2x4" in audit["targets"]
    target = audit["targets"]["tp_2x4"]
    assert target["collective_bytes_per_step"] > 0
    assert target["hbm_per_device_bytes"] > 0


def test_write_detail_carries_prec_audit_record(tmp_path):
    """BENCH_DETAIL.json carries the statically-audited numerics (fp32-
    bytes fraction of the traced step, widen/narrow cast counts) from
    the committed numerics budgets the precision CI gate verifies."""
    path = tmp_path / "BENCH_DETAIL.json"
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path))
    audit = json.loads(path.read_text())["prec_audit"]
    assert 0.0 < audit["fp32_bytes_fraction"] < 1.0
    assert audit["narrow_casts"] > 0
    assert audit["source"] == "tests/fixtures/budgets/prec"
    # Per-target breakdown: every committed numerics budget shows up.
    assert "tp_2x4" in audit["targets"]
    target = audit["targets"]["tp_2x4"]
    assert 0.0 < target["fp32_bytes_fraction"] < 1.0
    assert target["widen_casts"] > 0


def test_write_detail_carries_serve_audit_record(tmp_path):
    """BENCH_DETAIL.json carries the statically-predicted serving
    latency/HBM record (from the committed serving budgets the serve
    CI gate verifies), and — when a measured serve record rides along —
    the predicted-vs-measured ITL calibration."""
    path = tmp_path / "BENCH_DETAIL.json"
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path))
    audit = json.loads(path.read_text())["serve_audit"]
    assert audit["predicted_itl_us"] > 0
    assert audit["predicted_ttft_us"] > audit["predicted_itl_us"]
    assert audit["hbm_total_bytes"] > 0
    assert audit["source"] == "tests/fixtures/budgets/serve"
    for name in ("tiny", "charlm", "gpt2_geom"):
        target = audit["targets"][name]
        assert target["predicted_itl_us"] > target["itl_floor_us"] > 0
        assert target["overfetch_ratio"] >= 1.0


def test_serve_audit_summary_missing_budgets_is_none(tmp_path):
    """A checkout without committed serving budgets must not break
    emission."""
    assert bench.serve_audit_summary(
        None, str(tmp_path / "nowhere")
    ) is None


def test_calib_summary_reads_committed_budgets():
    """The budget half of the calib record (live=False skips the
    capture leg): per-target |calibration error| + unjoined fraction
    from the records the calib CI gate verifies."""
    out = bench.calib_summary(live=False)
    assert out is not None
    assert out["source"] == "tests/fixtures/budgets/calib"
    for name in ("gpt2_sentinel", "fsdp_1x8", "serve_decode"):
        assert 0 < out["targets"][name]["abs_calib_error"] <= 1.5
    # Worst-case headline across targets.
    assert out["abs_calib_error"] >= out["targets"]["gpt2_sentinel"][
        "abs_calib_error"
    ]


def test_calib_summary_missing_budgets_is_none(tmp_path):
    assert bench.calib_summary(str(tmp_path / "nowhere"),
                               live=False) is None


def test_write_detail_carries_calib_record(tmp_path):
    """BENCH_DETAIL.json carries the measured-vs-predicted record, and a
    probe-less rerun must not drop a previously-written one."""
    path = tmp_path / "BENCH_DETAIL.json"
    calib = {
        "abs_calib_error": 0.99,
        "targets": {"gpt2_sentinel": {"abs_calib_error": 0.99,
                                      "unjoined_fraction": 0.32}},
        "live": {"gpt2_sentinel": {"measured_step_us": 64000.0,
                                   "device_matched": False}},
        "source": "tests/fixtures/budgets/calib",
    }
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path),
                       calib=calib)
    assert json.loads(path.read_text())["calib"] == calib
    # Probe-less rerun (calib=None) keeps the committed record.
    bench.write_detail({"mlp": _full_result("mlp")}, path=str(path))
    assert json.loads(path.read_text())["calib"] == calib


@pytest.mark.slow
def test_calib_summary_live_leg_captures_and_reconciles():
    """The live half: a real capture->parse->reconcile of the gpt2
    sentinel on this host. Slow: one AOT compile + a traced run."""
    out = bench.calib_summary()
    assert out is not None and "live" in out
    live = out["live"]["gpt2_sentinel"]
    assert live["measured_step_us"] > 0
    assert live["abs_calib_error"] is not None
    assert live["priced_for"] == "TPU v5 lite"
    assert isinstance(live["device_matched"], bool)


@pytest.mark.slow
def test_serve_calibration_ties_prediction_to_measured_record():
    """The calibration leg: feed serve_audit_summary a measured serve
    record (the shape serve_summary emits) and it must re-predict the
    SAME engine config and report the signed error. Slow: one AOT
    compile of the charlm-geometry programs."""
    measured = {"itl_ms": {"p50": 2.0}, "ttft_ms": {"p50": 20.0}}
    out = bench.serve_audit_summary(measured)
    assert out is not None and "calibration" in out
    calib = out["calibration"]
    assert calib["measured_itl_us"] == 2000.0
    assert calib["predicted_itl_us"] > 0
    expected = (calib["predicted_itl_us"] - 2000.0) / 2000.0
    assert calib["itl_calibration_error"] == pytest.approx(
        expected, abs=1e-3
    )
    assert calib["ttft_calibration_error"] is not None
    # This container benches on CPU: the kind is absent from the peak
    # table, the prediction prices the reference kind instead.
    assert calib["priced_for"]
    assert isinstance(calib["device_matched"], bool)


def test_write_detail_carries_tune_record(tmp_path):
    """BENCH_DETAIL.json carries the tuned-kernel config record
    (rocket_tpu.tune tables): one row per tunable kernel with its entry
    list — each entry keyed (device kind, shape bucket, dtype) and
    carrying the tuner-measured speedup — plus this run's device kind,
    so tuned-vs-default speedup is tracked per kernel per device kind."""
    from rocket_tpu.tune.space import TUNE_SPACES

    path = tmp_path / "BENCH_DETAIL.json"
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path))
    record = json.loads(path.read_text())["tune"]
    assert set(record["kernels"]) == set(TUNE_SPACES)
    for kernel, row in record["kernels"].items():
        assert isinstance(row["n_entries"], int) and row["n_entries"] >= 0
        assert len(row["entries"]) == row["n_entries"]
        for entry in row["entries"]:
            assert entry["device_kind"] and entry["shape_bucket"]
            assert entry["speedup"] > 1.0  # only wins are persisted
        assert isinstance(row["structural_axes"], list)
    # The structural-variant scoreboard (ISSUE 14) rides the same
    # record: a list (empty while the shipped tables carry no wins),
    # carried across probe-less runs like the rest.
    assert isinstance(record["structural_wins"], list)
    assert record["device_kind"]
    assert record["source"].endswith(os.path.join("tune", "configs"))


def test_tune_summary_missing_tables_is_none(tmp_path):
    """A checkout without the tune config tables must not break
    emission."""
    assert bench.tune_summary(str(tmp_path / "nowhere")) is None
    path = tmp_path / "BENCH_DETAIL.json"
    real = bench.TUNE_CONFIGS_DIR
    bench.TUNE_CONFIGS_DIR = str(tmp_path / "nowhere")
    try:
        bench.write_detail({"mlp": _full_result("mlp")}, path=str(path))
    finally:
        bench.TUNE_CONFIGS_DIR = real
    assert "tune" not in json.loads(path.read_text())


def test_tune_summary_reports_table_entries(tmp_path):
    """A table with a tuned entry surfaces its speedup row and device
    kind in the summary (the shape the tuner's --update-table writes)."""
    from rocket_tpu.tune.space import TUNE_SPACES
    from rocket_tpu.tune.table import write_table

    for kernel in TUNE_SPACES:
        write_table(kernel, [], configs_dir=str(tmp_path))
    write_table("flash_fwd", [{
        "device_kind": "TPU v5 lite", "dtype": "bfloat16",
        "shape": {"t": 1024, "d": 64, "h": 12, "h_kv": 12, "causal": True},
        "shape_bucket": "t1024_d64_h12_h_kv12_causalt",
        "config": {"block_q": 256, "block_k": 256},
        "default_us": 100.0, "tuned_us": 90.0, "speedup": 1.111,
    }], configs_dir=str(tmp_path))
    summary = bench.tune_summary(str(tmp_path))
    row = summary["kernels"]["flash_fwd"]
    assert row["n_entries"] == 1
    assert row["entries"][0]["speedup"] == 1.111
    assert summary["table_device_kinds"] == ["TPU v5 lite"]


def test_prec_audit_summary_missing_budgets_is_none(tmp_path):
    """A checkout without committed numerics budgets must not break
    emission."""
    assert bench.prec_audit_summary(str(tmp_path / "nowhere")) is None
    path = tmp_path / "BENCH_DETAIL.json"
    real = bench.PREC_BUDGETS_DIR
    bench.PREC_BUDGETS_DIR = str(tmp_path / "nowhere")
    try:
        bench.write_detail({"mlp": _full_result("mlp")}, path=str(path))
    finally:
        bench.PREC_BUDGETS_DIR = real
    assert "prec_audit" not in json.loads(path.read_text())


def test_shard_audit_summary_missing_budgets_is_none(tmp_path):
    """A checkout without committed budgets must not break emission."""
    assert bench.shard_audit_summary(str(tmp_path / "nowhere")) is None
    # And the detail file simply omits the section.
    path = tmp_path / "BENCH_DETAIL.json"
    real = bench.BUDGETS_DIR
    bench.BUDGETS_DIR = str(tmp_path / "nowhere")
    try:
        bench.write_detail({"mlp": _full_result("mlp")}, path=str(path))
    finally:
        bench.BUDGETS_DIR = real
    assert "shard_audit" not in json.loads(path.read_text())


def test_write_detail_carries_health_sentinel_record(tmp_path):
    """BENCH_DETAIL.json carries the measured health-sentinel overhead
    (steps/sec with the in-step sentinels + lax.cond gate on vs off) when
    main() hands a probe record over — and simply omits the section when
    the probe was skipped or failed."""
    path = tmp_path / "BENCH_DETAIL.json"
    probe = {
        "steps_per_sec_baseline": 150.0,
        "steps_per_sec_with_sentinels": 148.5,
        "overhead_frac": 0.01,
        "action": "skip_step",
        "anomalies": 0,
        "skipped_steps": 0,
        "config": "mlp",
    }
    bench.write_detail({"mlp": _full_result("mlp")}, path=str(path),
                       health=probe)
    record = json.loads(path.read_text())["health_sentinels"]
    assert record["overhead_frac"] == 0.01
    assert record["anomalies"] == 0

    bench.write_detail({"mlp": _full_result("mlp")}, path=str(path))
    assert "health_sentinels" not in json.loads(path.read_text())


def test_write_detail_carries_resilience_record(tmp_path):
    """BENCH_DETAIL.json carries the supervised-restart probe's headline
    (goodput under one injected kill through the real supervisor) when
    main() hands a record over — and omits the section otherwise."""
    path = tmp_path / "BENCH_DETAIL.json"
    probe = {
        "outcome": "completed",
        "restarts": 1,
        "generations": 2,
        "goodput_fraction": 0.97,
        "total_wall_s": 12.3,
        "target_step": 60,
        "fault": "kill:step=23",
    }
    bench.write_detail({"mlp": _full_result("mlp")}, path=str(path),
                       resilience=probe)
    record = json.loads(path.read_text())["resilience"]
    assert record["goodput_fraction"] == 0.97
    assert record["restarts"] == 1 and record["outcome"] == "completed"

    bench.write_detail({"mlp": _full_result("mlp")}, path=str(path))
    assert "resilience" not in json.loads(path.read_text())


def test_write_detail_partial_run_keeps_gpt2_headline(tmp_path):
    """The merged record's headline must stay gpt2 after a debug run of
    a different config."""
    path = tmp_path / "BENCH_DETAIL.json"
    full = {name: _full_result(name) for name in bench.BENCHES}
    bench.write_detail(full, path=str(path))
    bench.write_detail({"mlp": _full_result("mlp")}, path=str(path))
    detail = json.loads(path.read_text())
    assert detail["headline_metric"] == bench.METRIC_NAMES["gpt2"]


def test_write_detail_carries_overlap_record(tmp_path):
    path = tmp_path / "detail.json"
    overlap = {
        "targets": {
            "tp_1x8": {
                "overlap": {"collective_bytes_per_step": 7600432,
                            "exposed_comm_us": 70.0},
                "baseline": {"collective_bytes_per_step": 14176944,
                             "exposed_comm_us": 147.5},
                "bytes_ratio": 1.865,
                "exposed_comm_drop_frac": 0.5255,
            }
        },
        "device_kind": "TPU v5 lite",
        "wire_dtype": "bfloat16",
    }
    bench.write_detail(
        {"gpt2": _full_result("gpt2")}, path=str(path), overlap=overlap
    )
    detail = json.loads(path.read_text())
    rec = detail["overlap"]["targets"]["tp_1x8"]
    assert rec["bytes_ratio"] == 1.865
    assert rec["exposed_comm_drop_frac"] > 0.4
    # A later run without the probe must not drop the committed record.
    bench.write_detail({"gpt2": _full_result("gpt2")}, path=str(path))
    assert "overlap" in json.loads(path.read_text())


def test_overlap_summary_shapes_real_targets():
    summary = bench.overlap_summary(targets=("tp_2x4_eval",))
    assert summary is not None
    rec = summary["targets"]["tp_2x4_eval"]
    assert rec["overlap"]["collective_bytes_per_step"] > 0
    assert rec["baseline"]["collective_bytes_per_step"] > 0
    # The overlapped eval forward moves no MORE than the GSPMD baseline.
    assert rec["bytes_ratio"] >= 1.0
    assert "exposed_comm_drop_frac" in rec
