"""Tracker: buffer lifecycle, sync-boundary flush, jsonl backend output,
image buffer routing."""

import json
import os

import numpy as np

from rocket_tpu.core.attributes import Attributes
from rocket_tpu.core.tracker import Tracker


class SpyBackend:
    def __init__(self):
        self.scalars = []
        self.images = []
        self.closed = False

    def log_scalars(self, scalars, step):
        self.scalars.append((step, dict(scalars)))

    def log_images(self, images, step):
        self.images.append((step, dict(images)))

    def close(self):
        self.closed = True


def run_epoch(tracker, waves, mode="train"):
    """Drive one epoch of `waves` dispatch waves by hand."""
    attrs = Attributes()
    attrs.mode = mode
    tracker.set(attrs)
    for wave in waves:
        attrs.sync_gradients = wave.get("sync", True)
        for key, value in wave.get("scalars", {}).items():
            attrs.tracker.scalars[key] = value
        if wave.get("image") is not None:
            attrs.tracker.images["sample"] = wave["image"]
        tracker.launch(attrs)
    tracker.reset(attrs)
    return attrs


def test_tracker_flushes_on_sync_boundary_and_buffers_images():
    spy = SpyBackend()
    tracker = Tracker(project="t")
    tracker._backend = spy  # bypass setup's backend construction

    img = np.zeros((4, 4, 3), np.float32)
    run_epoch(
        tracker,
        [
            {"scalars": {"loss": 1.0}, "sync": True},
            # Off-boundary: a DISTINCT key buffered, not flushed this wave.
            {"scalars": {"aux": 2.0}, "sync": False},
            {"scalars": {"loss": 3.0}, "image": img, "sync": True},
        ],
    )
    # Exactly the two boundary waves flushed — an every-wave flush or a
    # dropped off-boundary buffer would both change this.
    assert len(spy.scalars) == 2, spy.scalars
    assert spy.scalars[0][1] == {"loss": 1.0}
    # The off-boundary 'aux' value rides into the next boundary flush.
    assert spy.scalars[1][1] == {"aux": 2.0, "loss": 3.0}
    assert len(spy.images) == 1 and spy.images[0][1]["sample"] is not None


def test_jsonl_backend_writes_records(tmp_path):
    from rocket_tpu.core.tracker import JsonlBackend

    backend = JsonlBackend("proj", directory=str(tmp_path))
    backend.log_scalars({"loss": 0.5}, step=3)
    backend.close()
    with open(os.path.join(str(tmp_path), "proj.jsonl")) as f:
        record = json.loads(f.read().splitlines()[-1])
    assert record["step"] == 3 and record["loss"] == 0.5


def test_register_custom_backend_and_instance(runtime):
    from rocket_tpu.core.tracker import register_tracker_backend

    # (a) Registered factory, selected by name.
    made = []

    class CustomBackend(SpyBackend):
        def __init__(self, project, directory):
            super().__init__()
            made.append((project, directory))

    register_tracker_backend("custom_spy", CustomBackend)
    try:
        tracker = Tracker(backend="custom_spy", project="p", directory="d",
                          runtime=runtime)
        tracker.setup()
        assert made == [("p", "d")]
        run_epoch(tracker, [{"scalars": {"x": 1.0}, "sync": True}])
        assert tracker._backend.scalars[0][1] == {"x": 1.0}
    finally:
        from rocket_tpu.core import tracker as tracker_mod

        tracker_mod._BACKENDS.pop("custom_spy", None)

    # (b) Ready duck-typed instance passed directly.
    spy = SpyBackend()
    t2 = Tracker(backend=spy, project="p", runtime=runtime)
    t2.setup()
    assert t2._backend is spy
    assert runtime.get_tracker("SpyBackend") is spy

    # (c) Instance missing the contract is rejected up front.
    import pytest

    with pytest.raises(RuntimeError, match="lacks"):
        Tracker(backend=object(), runtime=runtime)


def test_wandb_backend_through_fake_module(monkeypatch, tmp_path):
    """The shipped wandb adapter speaks the real wandb API shape
    (round-3 verdict ask #8) — proven against a stand-in module."""
    import sys
    import types

    calls = {"log": [], "finish": 0, "init": []}

    class FakeRun:
        def log(self, data, step=None):
            calls["log"].append((step, data))

        def finish(self):
            calls["finish"] += 1

    fake = types.ModuleType("wandb")
    fake.init = lambda project=None, dir=None: (
        calls["init"].append((project, dir)) or FakeRun()
    )
    fake.Image = lambda arr: ("wandb-image", np.asarray(arr).shape)
    monkeypatch.setitem(sys.modules, "wandb", fake)

    from rocket_tpu.core.tracker import WandbBackend

    backend = WandbBackend("proj", str(tmp_path))
    backend.log_scalars({"loss": 1.5}, 3)
    backend.log_images({"img": np.zeros((2, 2, 3))}, 4)
    backend.close()

    assert calls["init"] == [("proj", str(tmp_path))]
    assert calls["log"][0] == (3, {"loss": 1.5})
    assert calls["log"][1][0] == 4
    assert calls["log"][1][1]["img"] == ("wandb-image", (2, 2, 3))
    assert calls["finish"] == 1


def test_wandb_missing_falls_back_to_jsonl(monkeypatch, tmp_path, runtime):
    import sys

    monkeypatch.setitem(sys.modules, "wandb", None)  # import -> ImportError
    from rocket_tpu.core.tracker import JsonlBackend

    tracker = Tracker(
        backend="wandb", project="p", directory=str(tmp_path), runtime=runtime
    )
    tracker.setup()
    assert isinstance(tracker._backend, JsonlBackend)
