"""sched_audit: HLO-schedule parsing, the roofline cost model, the
two-stream simulation, per-RKT5xx-rule true positives and clean
negatives, pallas fact collection, the schedule budget gate (RKT506)
and the builtin self-gate / seeded-bad demo targets.
"""

import jax
import jax.numpy as jnp
import pytest

from rocket_tpu.analysis import budgets
from rocket_tpu.analysis.rules.sched_rules import (
    check_convoys,
    check_exposed_comm,
    check_memory_bound,
    check_mfu_floor,
    check_pallas,
)
from rocket_tpu.analysis.sched_audit import (
    SCHED_TARGETS,
    OpCost,
    PallasFact,
    collect_pallas_facts,
    cost_ops,
    parse_hlo_module,
    predict_compiled,
    run_sched_target,
    simulate,
)
from rocket_tpu.utils.perf import device_spec

SPEC = device_spec("TPU v5 lite")


def rules_in(findings):
    return sorted({f.rule for f in findings})


# -- HLO parsing -------------------------------------------------------------

HLO = """\
HloModule jit_step, is_scheduled=true, num_partitions=8

%add.1 (x: f32[], y: f32[]) -> f32[] {
  %x = f32[] parameter(0)
  %y = f32[] parameter(1)
  ROOT %a = f32[] add(f32[] %x, f32[] %y)
}

%fused_computation.1 (p0: f32[128,256], p1: f32[256,64]) -> f32[128,64] {
  %p0 = f32[128,256]{1,0} parameter(0)
  %p1 = f32[256,64]{1,0} parameter(1)
  ROOT %d.i = f32[128,64]{1,0} dot(f32[128,256]{1,0} %p0, f32[256,64]{1,0} %p1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}

ENTRY %main_spmd (param.0: f32[128,256], param.1: f32[256,64]) -> f32[128,64] {
  %param.0 = f32[128,256]{1,0} parameter(0), sharding={replicated}
  %param.1 = f32[256,64]{1,0} parameter(1)
  %dot.1 = f32[128,64]{1,0} dot(f32[128,256]{1,0} %param.0, f32[256,64]{1,0} %param.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}, metadata={op_name="jit(step)/dot_general" source_file="/repo/nn/layers.py" source_line=66}
  %fusion.1 = f32[128,64]{1,0} fusion(f32[128,256]{1,0} %param.0, f32[256,64]{1,0} %param.1), kind=kLoop, calls=%fused_computation.1
  %bf.1 = bf16[128,64]{1,0} convert(f32[128,64]{1,0} %dot.1)
  %dot.2 = bf16[128,64]{1,0} dot(bf16[128,64]{1,0} %bf.1, bf16[128,64]{1,0} %bf.1), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  %all-reduce.0 = f32[128,64]{1,0} all-reduce(f32[128,64]{1,0} %fusion.1), channel_id=1, replica_groups={{0,1,2,3},{4,5,6,7}}, use_global_device_ids=true, to_apply=%add.1
  %all-gather-start.1 = (f32[128,64]{1,0}, f32[512,64]{1,0}) all-gather-start(f32[128,64]{1,0} %all-reduce.0), channel_id=2, replica_groups=[2,4]<=[8], dimensions={0}
  %all-gather-done.1 = f32[512,64]{1,0} all-gather-done((f32[128,64]{1,0}, f32[512,64]{1,0}) %all-gather-start.1)
  ROOT %slice.1 = f32[128,64]{1,0} slice(f32[512,64]{1,0} %all-gather-done.1), slice={[0:128], [0:64]}
}
"""


def test_parse_hlo_module_entry_and_computations():
    entry, comps = parse_hlo_module(HLO)
    assert [i.name for i in entry] == [
        "param.0", "param.1", "dot.1", "fusion.1", "bf.1", "dot.2",
        "all-reduce.0", "all-gather-start.1", "all-gather-done.1",
        "slice.1",
    ]
    assert "fused_computation.1" in comps and "add.1" in comps
    by = {i.name: i for i in entry}
    # Operands reference instructions only — called computations
    # (calls=/to_apply=) must NOT leak into the operand list.
    assert by["fusion.1"].operands == ("param.0", "param.1")
    assert by["fusion.1"].called == ("fused_computation.1",)
    assert by["all-reduce.0"].called == ("add.1",)
    # Tuple-typed async result: bytes cover the tuple, shape is the
    # first element's.
    assert by["all-gather-start.1"].result_bytes == (128 * 64 + 512 * 64) * 4


def test_cost_model_dot_flops_and_dtype_factor():
    entry, comps = parse_hlo_module(HLO)
    ops = {o.name: o for o in cost_ops(entry, comps, SPEC)}
    # 2 * M * N * K, f32 dots at half the bf16 peak.
    assert ops["dot.1"].flops == 2 * 128 * 64 * 256
    assert ops["dot.2"].flops == 2 * 128 * 64 * 64
    f32_time = ops["dot.1"].time_s
    assert f32_time >= ops["dot.1"].flops / (SPEC.flops_bf16 * 0.5) - 1e-15
    # Fusion FLOPs come from the called computation's dot.
    assert ops["fusion.1"].flops == 2 * 128 * 64 * 256
    # Parameters are free.
    assert ops["param.0"].kind == "free"


def test_cost_model_collectives_ring_bytes_and_async_done_free():
    entry, comps = parse_hlo_module(HLO)
    ops = {o.name: o for o in cost_ops(entry, comps, SPEC)}
    ar = ops["all-reduce.0"]
    assert ar.is_comm
    result = 128 * 64 * 4
    assert ar.comm_bytes == int(2 * (4 - 1) / 4 * result)
    # iota-form replica groups ([2,4]<=[8] -> group size 4); async start
    # costs the final tuple element, the done half is a free join.
    ag = ops["all-gather-start.1"]
    assert ag.comm_bytes == int((4 - 1) / 4 * (512 * 64 * 4))
    assert ops["all-gather-done.1"].time_s == 0.0


def test_cost_model_prices_cross_slice_collectives_at_dcn():
    """slice_size declares a multi-slice topology: groups confined to
    one slice keep ICI pricing; groups (or iota groups wider than a
    slice) spanning the boundary drop to ``spec.dcn_bw``."""
    entry, comps = parse_hlo_module(HLO)
    ici = {o.name: o for o in cost_ops(entry, comps, SPEC)}
    # all-reduce groups {0,1,2,3},{4,5,6,7} stay inside 4-wide slices;
    # the iota all-gather ([2,4]<=[8], group size 4) does too.
    same = {o.name: o for o in cost_ops(entry, comps, SPEC, slice_size=4)}
    assert not same["all-reduce.0"].is_dcn
    assert not same["all-gather-start.1"].is_dcn
    assert same["all-reduce.0"].time_s == ici["all-reduce.0"].time_s
    # 2-wide slices split both: every group now crosses a boundary and
    # the same bytes take dcn_bw instead of ici_bw.
    cross = {o.name: o for o in cost_ops(entry, comps, SPEC, slice_size=2)}
    assert cross["all-reduce.0"].is_dcn
    assert cross["all-gather-start.1"].is_dcn
    ar = cross["all-reduce.0"]
    assert ar.time_s > ici["all-reduce.0"].time_s
    assert abs(
        ar.time_s - (ar.comm_bytes / SPEC.dcn_bw + 1e-6)
    ) < 1e-12


# -- the simulation ----------------------------------------------------------


def mk_op(name, kind, time_s, operands=(), opcode=None, comm_bytes=0,
          hbm_bytes=0, flops=0.0):
    is_comm = kind == "comm"
    return OpCost(
        name=name, opcode=opcode or ("all-reduce" if is_comm else "fusion"),
        kind=kind, time_s=time_s, flops=flops, hbm_bytes=hbm_bytes,
        comm_bytes=comm_bytes, is_comm=is_comm, operands=tuple(operands),
    )


def test_sync_simulation_exposes_blocking_collective():
    ops = [
        mk_op("c", "comm", 10e-6, comm_bytes=1 << 20),
        mk_op("a", "memory", 4e-6),
        mk_op("b", "memory", 6e-6),
        mk_op("d", "memory", 2e-6, operands=("c",)),
    ]
    sim = simulate(ops, overlap=False)
    # Sync collective blocks: 10us exposed, then 12us of compute.
    assert sim.makespan_s == pytest.approx(22e-6)
    assert sim.exposed_comm_s == pytest.approx(10e-6)
    assert sim.memory_bound_s == pytest.approx(12e-6)
    # Attribution identity: makespan = compute + memory + exposed + stall.
    assert sim.makespan_s == pytest.approx(
        sim.compute_bound_s + sim.memory_bound_s + sim.exposed_comm_s
        + sim.stall_s
    )


def test_dataflow_simulation_hides_collective_behind_independent_compute():
    ops = [
        mk_op("c", "comm", 10e-6, comm_bytes=1 << 20),
        mk_op("a", "memory", 4e-6),
        mk_op("b", "memory", 6e-6),
        mk_op("d", "memory", 2e-6, operands=("c",)),
    ]
    ideal = simulate(ops, overlap=True)
    # a/b (10us independent compute) hide the 10us collective entirely.
    assert ideal.makespan_s == pytest.approx(12e-6)
    assert ideal.exposed_comm_s == pytest.approx(0.0)


def test_sync_collective_after_busy_compute_cannot_time_travel():
    """A sync collective scheduled after compute is issued by the
    in-order sequencer WHEN the stream reaches it — it must not float
    back to its dependency time and cost nothing."""
    ops = [
        mk_op("a", "memory", 10e-6),
        mk_op("c", "comm", 5e-6, comm_bytes=1 << 20),
        mk_op("d", "memory", 1e-6, operands=("c",)),
    ]
    sim = simulate(ops, overlap=False)
    assert sim.makespan_s == pytest.approx(16e-6)
    assert sim.exposed_comm_s == pytest.approx(5e-6)


def test_dataflow_simulation_keeps_structural_exposure():
    # The collective feeds the ONLY compute op: nothing can hide it.
    ops = [
        mk_op("c", "comm", 10e-6, comm_bytes=1 << 20),
        mk_op("d", "memory", 2e-6, operands=("c",)),
    ]
    ideal = simulate(ops, overlap=True)
    assert ideal.exposed_comm_s == pytest.approx(10e-6)


# -- RKT501 ------------------------------------------------------------------


def test_exposed_comm_fires_only_on_hideable_exposure():
    ops = [
        mk_op("c", "comm", 50e-6, comm_bytes=8 << 20),
        mk_op("a", "memory", 40e-6),
        mk_op("b", "memory", 40e-6),
        mk_op("d", "memory", 2e-6, operands=("c",)),
    ]
    sim = simulate(ops, overlap=False)
    ideal = simulate(ops, overlap=True)
    findings = check_exposed_comm(sim, ideal, label="t")
    assert rules_in(findings) == ["RKT501"]
    assert "could hide" in findings[0].message

    # Structural-only exposure (no independent compute): silent.
    ops2 = [
        mk_op("c", "comm", 50e-6, comm_bytes=8 << 20),
        mk_op("d", "memory", 2e-6, operands=("c",)),
    ]
    findings2 = check_exposed_comm(
        simulate(ops2, overlap=False), simulate(ops2, overlap=True),
        label="t",
    )
    assert findings2 == []


# -- RKT502 ------------------------------------------------------------------


def test_convoy_detection_and_gap_break():
    tiny = [mk_op(f"c{i}", "comm", 1e-6, comm_bytes=1024)
            for i in range(8)]
    assert rules_in(check_convoys(tiny, label="t")) == ["RKT502"]

    # A big compute op between them breaks the run below convoy_min.
    split = tiny[:3] + [mk_op("f", "memory", 5e-6, hbm_bytes=1 << 20)] \
        + tiny[3:6] + [mk_op("g", "memory", 5e-6, hbm_bytes=1 << 20)] \
        + tiny[6:]
    assert check_convoys(split, label="t") == []

    # Tiny interleaved fusions (scalar fixups) do NOT break the convoy.
    laced = []
    for i, op in enumerate(tiny):
        laced.append(op)
        laced.append(mk_op(f"s{i}", "memory", 1e-9, hbm_bytes=256))
    assert rules_in(check_convoys(laced, label="t")) == ["RKT502"]

    # Large-payload collectives are bandwidth-, not latency-bound.
    big = [mk_op(f"c{i}", "comm", 100e-6, comm_bytes=64 << 20)
           for i in range(8)]
    assert check_convoys(big, label="t") == []


# -- RKT503 ------------------------------------------------------------------


def test_memory_bound_gate_and_small_op_exemption():
    heavy = [mk_op(f"m{i}", "memory", 30e-6, hbm_bytes=4 << 20)
             for i in range(3)]
    light = [mk_op("x", "compute", 10e-6, flops=1e9)]
    findings = check_memory_bound(
        heavy + light, makespan_s=100e-6, ridge=SPEC.ridge, label="t"
    )
    assert rules_in(findings) == ["RKT503"]

    # The same time spent in SMALL memory-bound ops is policy, not a
    # hazard (tiny models are all memory-bound).
    small = [mk_op(f"m{i}", "memory", 30e-6, hbm_bytes=1 << 10)
             for i in range(3)]
    assert check_memory_bound(
        small + light, makespan_s=100e-6, ridge=SPEC.ridge, label="t"
    ) == []


# -- RKT504 ------------------------------------------------------------------


def _fact(blocks, full=None, vmem=0):
    return PallasFact(
        name="k", grid=(4,), blocks=tuple(blocks),
        full_shapes=full or {}, vmem_bytes_est=vmem,
    )


def test_pallas_alignment_and_vmem_checks():
    aligned = _fact([(((16, 128)), "float32")], vmem=1 << 20)
    assert check_pallas([aligned], SPEC.vmem_bytes) == []

    misaligned = _fact([((7, 100), "float32")])
    findings = check_pallas([misaligned], SPEC.vmem_bytes)
    assert rules_in(findings) == ["RKT504"]
    assert "% 128" in findings[0].message

    # bf16 sublane minimum is 16: an 8-sublane bf16 block misfits.
    bf16 = _fact([((8, 128), "bfloat16")])
    assert rules_in(check_pallas([bf16], SPEC.vmem_bytes)) == ["RKT504"]

    # Full-dimension blocks are exempt from the lane rule (mosaic allows
    # block == whole array dim).
    full = _fact(
        [((8, 100), "float32")],
        full={((8, 100), "float32"): (64, 100)},
    )
    assert check_pallas([full], SPEC.vmem_bytes) == []

    over = _fact([((8, 128), "float32")], vmem=SPEC.vmem_bytes + 1)
    findings = check_pallas([over], SPEC.vmem_bytes)
    assert rules_in(findings) == ["RKT504"]
    assert "VMEM" in findings[0].message


def test_collect_pallas_facts_from_traced_step():
    from jax.experimental import pallas as pl

    def kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.0

    def step(variables, batch):
        x = batch["x"]
        return variables, pl.pallas_call(
            kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            grid=(2,),
            in_specs=[pl.BlockSpec((128, 256), lambda i: (i, 0))],
            out_specs=pl.BlockSpec((128, 256), lambda i: (i, 0)),
        )(x).sum()

    variables = {"params": {}, "state": {}}
    batch = {"x": jax.ShapeDtypeStruct((256, 256), jnp.float32)}
    facts = collect_pallas_facts(step, variables, batch)
    assert len(facts) == 1
    assert facts[0].grid == (2,)
    assert ((128, 256), "float32") in facts[0].blocks
    # in + out double-buffered: 2 * 2 * 128*256*4
    assert facts[0].vmem_bytes_est == 2 * 2 * 128 * 256 * 4


# -- RKT505 ------------------------------------------------------------------


def test_mfu_floor():
    assert check_mfu_floor(0.5, 0.4) == []
    assert check_mfu_floor(None, 0.4) == []
    assert check_mfu_floor(0.5, 0.0) == []
    assert rules_in(check_mfu_floor(0.3, 0.4)) == ["RKT505"]


# -- RKT506: the schedule budget gate ----------------------------------------


def test_sched_budget_diff_gates_step_time_and_exposure(tmp_path):
    record = {"predicted_step_time_us": 100.0, "exposed_comm_us": 40.0}
    budgets.write_budget(str(tmp_path), "t", record)
    committed = budgets.load_budget(str(tmp_path), "t")

    grown = {"predicted_step_time_us": 120.0, "exposed_comm_us": 40.0}
    findings = budgets.diff_budget(
        "t", committed, grown, keys=budgets.SCHED_GATED_KEYS,
        rule="RKT506", family="sched",
    )
    assert rules_in(findings) == ["RKT506"]
    assert findings[0].path == "<sched:t>"

    shrunk = {"predicted_step_time_us": 80.0, "exposed_comm_us": 20.0}
    assert budgets.diff_budget(
        "t", committed, shrunk, keys=budgets.SCHED_GATED_KEYS,
        rule="RKT506", family="sched",
    ) == []


# -- builtin targets ---------------------------------------------------------


def test_builtin_self_gate_targets_are_clean():
    """THE acceptance gate: the repo's own steps on the repo's own rule
    sets, roofline-simulated — zero findings, and every compiled target
    attributes its predicted step time."""
    for name in ("tp_2x4", "fsdp_1x8", "tp_2x4_eval"):
        report = run_sched_target(SCHED_TARGETS[name])
        assert report.findings == [], (name, report.findings)
        fr = report.record["fractions"]
        assert set(fr) == {"compute", "memory", "exposed_comm", "stall"}
        assert sum(fr.values()) == pytest.approx(1.0, abs=0.01)
        for key in budgets.SCHED_GATED_KEYS:
            assert report.record[key] >= 0


def test_resnet_target_counts_conv_flops_and_bn_collectives():
    report = run_sched_target(SCHED_TARGETS["dp_resnet_1x8"])
    assert report.findings == [], report.findings
    # Conv FLOPs dominate: a CIFAR ResNet-18 fwd+bwd step at B=64 is
    # ~3 * 2 * 0.56 GMACs/sample * 64 — the parser must see them.
    assert report.record["flops_per_step"] > 1e10
    # Sync-BN: ONE stacked stats all-reduce per BN layer in forward
    # (the fused-moments fix this auditor motivated), not two.
    assert report.record["n_collectives"] < 120


def test_flash_target_audits_real_kernels_jaxpr_only():
    report = run_sched_target(SCHED_TARGETS["tp_flash"])
    assert report.findings == [], report.findings
    assert report.record == {}  # jaxpr-only: no HLO, no budget record
    assert len(report.pallas) >= 2  # fwd + bwd kernels
    assert all(fact.blocks for fact in report.pallas)


def test_badsched_demo_reports_schedule_families():
    report = run_sched_target(SCHED_TARGETS["badsched"])
    assert {"RKT501", "RKT502", "RKT503", "RKT505"} <= set(
        rules_in(report.findings)
    )


def test_badpallas_demo_reports_block_misfits():
    report = run_sched_target(SCHED_TARGETS["badpallas"])
    assert rules_in(report.findings) == ["RKT504"]
    messages = " ".join(f.message for f in report.findings)
    assert "% 128" in messages and "VMEM" in messages


def test_predict_compiled_rejects_unknown_device_kind():
    with pytest.raises(ValueError):
        predict_compiled(HLO, device_kind="TPU v99")


def test_predict_compiled_record_shape_on_snippet():
    scheduled, ideal, record = predict_compiled(HLO)
    assert record["n_collectives"] == 2
    assert record["predicted_step_time_us"] > 0
    assert ideal.makespan_s <= scheduled.makespan_s + 1e-12
    assert record["device_kind"] == "TPU v5 lite"


# -- PR 12: permute pricing + async-DMA semantics + badoverlap ---------------


def test_collective_permute_priced_per_link():
    """A ppermute hop moves its chunk over ONE ICI link; bulk
    collectives drive every link — the same bytes must cost more as a
    permute than as an all-gather."""
    from rocket_tpu.analysis.sched_audit import cost_ops, parse_hlo_module
    from rocket_tpu.utils.perf import device_spec

    spec = device_spec("TPU v5 lite")
    hlo = """
HloModule m, is_scheduled=true

ENTRY %main (p: f32[1024,256]) -> f32[1024,256] {
  %p = f32[1024,256] parameter(0)
  %perm = f32[1024,256] collective-permute(f32[1024,256] %p), source_target_pairs={{0,1},{1,0}}
  ROOT %ag = f32[1024,256] all-gather(f32[1024,256] %perm), replica_groups={{0,1}}, dimensions={0}
}
"""
    entry, comps = parse_hlo_module(hlo)
    ops = {op.name: op for op in cost_ops(entry, comps, spec)}
    bytes_each = 1024 * 256 * 4
    # permute: one hop of the full buffer at LINK bandwidth.
    assert ops["perm"].time_s == pytest.approx(
        bytes_each / spec.ici_link_bw + 1e-6
    )
    # all-gather: ring bytes at AGGREGATE bandwidth.
    assert ops["ag"].time_s == pytest.approx(
        (bytes_each // 2) / spec.ici_bw + 1e-6
    )
    assert ops["perm"].time_s > ops["ag"].time_s


def test_sync_sim_treats_permutes_as_async_dma():
    """collective-permute is an async DMA on TPU (XLA lowers it to
    -start/-done there); the CPU dump's sync spelling must not make the
    simulator block compute on it — only its CONSUMERS wait."""
    ops = [
        mk_op("c", "comm", 10e-6, opcode="collective-permute",
              comm_bytes=1 << 20),
        mk_op("a", "memory", 6e-6),
        mk_op("b", "memory", 6e-6),
        mk_op("d", "memory", 2e-6, operands=("c",)),
    ]
    sim = simulate(ops, overlap=False)
    # a/b run while the permute flies: makespan 12 + 2, exposure 0
    # (comm_busy never intersects compute idle until d, which is ready
    # at t=10 < compute_clock 12).
    assert sim.makespan_s == pytest.approx(14e-6)
    assert sim.exposed_comm_s == pytest.approx(0.0)
    # The sync spelling of a bulk collective still blocks.
    ops2 = [
        mk_op("c", "comm", 10e-6, opcode="all-reduce",
              comm_bytes=1 << 20),
        mk_op("a", "memory", 6e-6),
    ]
    sim2 = simulate(ops2, overlap=False)
    assert sim2.exposed_comm_s == pytest.approx(10e-6)


def test_badoverlap_demo_reports_convoy_and_exposure():
    """The seeded-bad unoverlapped shape — per-param grad psum convoy +
    a sync all-gather blocking independent compute — must still be
    NAMED by the rules the overlapped paths were built to satisfy."""
    report = run_sched_target(SCHED_TARGETS["badoverlap"])
    found = set(rules_in(report.findings))
    assert {"RKT501", "RKT502"} <= found, found


def test_tp_targets_budget_exposed_comm_dropped():
    """The committed tp_1x8 schedule budget must hold the overlapped
    program's exposure: the acceptance floor (>= 40% below the
    pre-overlap 119.885us) is pinned so a regression cannot be
    re-committed unnoticed."""
    import json
    import os

    path = os.path.join(
        os.path.dirname(__file__), "fixtures", "budgets", "sched",
        "tp_1x8.json",
    )
    with open(path) as f:
        record = json.load(f)
    assert record["exposed_comm_us"] <= 119.885 * 0.6
