"""Two-process jax.distributed smoke test (VERDICT r1 item 9).

Spawns 2 real OS processes on CPU (2 virtual devices each → a 4-device
global mesh), joined through a localhost coordinator via the same env vars
``Runtime._maybe_initialize_distributed`` reads in production. Exercises the
branches that otherwise never run as true multihost: distributed init, the
all-rank barrier, per-host striped loading, cross-process training
collectives, and the sharded (gather-free) checkpoint save from BOTH hosts.
"""

import pytest
import os
import socket
import subprocess
import sys

_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO_ROOT"])

import numpy as np
import optax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.runtime.context import Runtime

runtime = Runtime(mesh_shape={"data": 4}, seed=0, project_dir=os.environ["OUT"])
assert jax.process_count() == 2, jax.process_count()
rank = runtime.process_index

# All-rank barrier (the reference's rank-0-only deadlock fixed).
runtime.wait_for_everyone()

rng = np.random.default_rng(0)
data = [
    {"image": rng.normal(size=8).astype(np.float32), "label": np.int32(i % 4)}
    for i in range(128)
]

def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()

model = MLP(in_features=8, num_classes=4, hidden=(16,))
ckpt_dir = os.path.join(os.environ["OUT"], "ckpts")
tree = rt.Launcher(
    [
        rt.Looper(
            [
                # device_cache off multihost -> striped streaming loader.
                rt.Dataset(data, batch_size=32),
                rt.Module(
                    model,
                    capsules=[
                        rt.Loss(cross_entropy),
                        rt.Optimizer(optim.adam(), learning_rate=1e-2),
                    ],
                ),
                rt.Checkpointer(output_dir=ckpt_dir, save_every=4),
            ],
            tag="train",
            progress=False,
        )
    ],
    num_epochs=1,
    runtime=runtime,
)
tree.launch()

# Both hosts contributed shard files; the index lists them.
step_dir = os.path.join(ckpt_dir, "4", "model_0")
assert os.path.exists(os.path.join(step_dir, f"shard_p{rank}.npz")), os.listdir(step_dir)
if rank == 0:
    assert os.path.exists(os.path.join(step_dir, "index.json"))

# Cross-process RESUME: a fresh tree restores the sharded checkpoint — each
# host reads only the chunks its addressable shards need (plus the other
# host's file for resharded regions) and lands on the saved step.
model2 = MLP(in_features=8, num_classes=4, hidden=(16,))
module2 = rt.Module(
    model2,
    capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)],
)
tree2 = rt.Launcher(
    [
        rt.Looper(
            [
                rt.Dataset(data, batch_size=32),
                module2,
                rt.Checkpointer(
                    output_dir=ckpt_dir, save_every=1000,
                    resume_from=os.path.join(ckpt_dir, "4"),
                    resume_capsules=False,
                ),
            ],
            tag="train",
            progress=False,
        )
    ],
    num_epochs=1,
    runtime=runtime,
)
attrs = rt.Attributes()
tree2.setup(attrs)
import numpy as _np
assert int(_np.asarray(module2.state["step"])) == 4, module2.state["step"]
assert module2._prepared.host_step == 4
tree2.destroy(attrs)
runtime.wait_for_everyone()

# Meter(gather_on="main") across processes: every rank participates in the
# gather collectives (no hang), but only rank 0 retains the global batch
# and accumulates host-path metrics.
from rocket_tpu.core.meter import Metric

class CountSamples(Metric):
    def __init__(self):
        super().__init__()
        self.total = 0

    def launch(self, attrs=None):
        self.total += int(attrs.batch["label"].shape[0])

    def reset(self, attrs=None):
        pass

counter = CountSamples()
model3 = MLP(in_features=8, num_classes=4, hidden=(16,))
rt.Launcher(
    [
        rt.Looper(
            [
                rt.Dataset(data, batch_size=32),
                rt.Module(model3),
                rt.Meter(["logits", "label"], [counter], gather_on="main"),
            ],
            tag="val",
            grad_enabled=False,
            progress=False,
        )
    ],
    num_epochs=1,
    runtime=runtime,
).launch()
expected = 128 if rank == 0 else 0
assert counter.total == expected, (rank, counter.total)
runtime.wait_for_everyone()
print(f"RANK{rank} OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_train_and_checkpoint(tmp_path):
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            JAX_PLATFORMS="cpu",
            REPO_ROOT=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            OUT=str(tmp_path),
        )
        # A worker must not inherit a single-process test runtime.
        env.pop("JAX_PLATFORM_NAME", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        outs.append(out)
        assert proc.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RANK{rank} OK" in out, out
