"""Two-process jax.distributed smoke test (VERDICT r1 item 9).

Spawns 2 real OS processes on CPU (2 virtual devices each → a 4-device
global mesh), joined through a localhost coordinator via the same env vars
``Runtime._maybe_initialize_distributed`` reads in production. Exercises the
branches that otherwise never run as true multihost: distributed init, the
all-rank barrier, per-host striped loading, cross-process training
collectives, the sharded (gather-free) checkpoint save from BOTH hosts, and
the main-process-only gating of the obs outputs (telemetry.json, the span
file and flight-recorder blackbox bundles are each written exactly once).
"""

import pytest
import os
import socket
import subprocess
import sys

# ---------------------------------------------------------------------------
# Backend capability probe (PR 5 note / ISSUE 7 satellite): some CPU-only
# containers ship a jax whose CPU backend cannot run cross-process
# collectives — the 2-proc spawn tests then fail at HEAD through no fault of
# the code under test. Probe once per session with a minimal 2-process
# psum; skip (not fail) the spawn tests when the backend can't do it. The
# probe only ever runs under --runslow (these tests are slow-marked), so
# the tier-1 fast tier stays deterministic and probe-free.
# ---------------------------------------------------------------------------

_PROBE = r"""
import os
import jax
jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=os.environ["JAX_COORDINATOR_ADDRESS"],
    num_processes=2,
    process_id=int(os.environ["JAX_PROCESS_ID"]),
)
import jax.numpy as jnp
from jax.experimental import multihost_utils
total = multihost_utils.process_allgather(
    jnp.ones(()) * (1 + jax.process_index())
).sum()
assert int(total) == 3, total
print("PROBE_OK", flush=True)
"""

_probe_result = {}


def _multiprocess_backend_ok() -> bool:
    """True when this jax build can run 2-process CPU collectives
    (memoized: one probe per test session). On failure the probe's
    evidence (exit state + output tail) is kept so the skip message can
    say exactly which capability is missing and why."""
    if "ok" not in _probe_result:
        port = _free_port()
        procs = []
        for rank in range(2):
            env = dict(os.environ)
            env.update(
                JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                JAX_NUM_PROCESSES="2",
                JAX_PROCESS_ID=str(rank),
                JAX_PLATFORMS="cpu",
                XLA_FLAGS="--xla_force_host_platform_device_count=1",
            )
            env.pop("JAX_PLATFORM_NAME", None)
            procs.append(subprocess.Popen(
                [sys.executable, "-c", _PROBE], env=env,
                stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
            ))
        ok = True
        detail = None
        for rank, proc in enumerate(procs):
            try:
                out, _ = proc.communicate(timeout=120)
            except subprocess.TimeoutExpired:
                for p in procs:
                    p.kill()
                    p.communicate()
                ok = False
                detail = "probe timed out after 120s (likely hung collective)"
                break
            if proc.returncode != 0 or "PROBE_OK" not in out:
                ok = False
                tail = out.strip().splitlines()[-1] if out.strip() else "(no output)"
                detail = (
                    f"probe rank {rank} exited {proc.returncode}: {tail[:200]}"
                )
        _probe_result["ok"] = ok
        _probe_result["detail"] = detail
    return _probe_result["ok"]


def _require_multiprocess_backend():
    if not _multiprocess_backend_ok():
        pytest.skip(
            "missing backend capability: cross-process collectives — this "
            "jax build's CPU backend cannot run a 2-process psum in this "
            f"container ({_probe_result.get('detail') or 'see probe'})"
        )


_WORKER = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO_ROOT"])

import numpy as np
import optax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.runtime.context import Runtime

# Per-process project dir: file-once-only assertions below never race the
# other rank — a file in proc1/ can only have been written by rank 1.
_proc_dir = os.path.join(os.environ["OUT"], f"proc{os.environ['JAX_PROCESS_ID']}")
runtime = Runtime(mesh_shape={"data": 4}, seed=0, project_dir=_proc_dir,
                  telemetry=True, health=True, anomaly_action="skip_step")
assert jax.process_count() == 2, jax.process_count()
rank = runtime.process_index

# All-rank barrier (the reference's rank-0-only deadlock fixed).
runtime.wait_for_everyone()

rng = np.random.default_rng(0)
data = [
    {"image": rng.normal(size=8).astype(np.float32), "label": np.int32(i % 4)}
    for i in range(128)
]

def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()

model = MLP(in_features=8, num_classes=4, hidden=(16,))
ckpt_dir = os.path.join(os.environ["OUT"], "ckpts")
tree = rt.Launcher(
    [
        rt.Looper(
            [
                # device_cache off multihost -> striped streaming loader.
                rt.Dataset(data, batch_size=32),
                rt.Module(
                    model,
                    capsules=[
                        rt.Loss(cross_entropy),
                        rt.Optimizer(optim.adam(), learning_rate=1e-2),
                    ],
                ),
                rt.Checkpointer(output_dir=ckpt_dir, save_every=4),
            ],
            tag="train",
            progress=False,
        )
    ],
    num_epochs=1,
    runtime=runtime,
)
tree.launch()

# Obs outputs are written EXACTLY once, by the main process: telemetry.json
# + spans land under rank 0's project dir only, and a forced flight-recorder
# dump writes a bundle on rank 0 and returns None elsewhere. Health
# sentinels ran multihost (replicated word, local-replica fetch).
_bundle = runtime.flight.dump("mp_forced")
_tel_dir = os.path.join(_proc_dir, "runs", "telemetry")
if rank == 0:
    assert os.path.exists(os.path.join(_tel_dir, "telemetry.json")), _tel_dir
    assert os.path.exists(os.path.join(_tel_dir, "spans.trace.json"))
    assert _bundle is not None and os.path.isdir(_bundle), _bundle
    import glob as _glob
    assert len(_glob.glob(os.path.join(_tel_dir, "blackbox", "*"))) == 1
else:
    assert not os.path.exists(os.path.join(_tel_dir, "telemetry.json")), (
        "non-main process wrote telemetry.json")
    assert not os.path.exists(os.path.join(_tel_dir, "spans.trace.json")), (
        "non-main process wrote the span file")
    assert _bundle is None
    assert not os.path.isdir(os.path.join(_tel_dir, "blackbox")), (
        "non-main process wrote a blackbox bundle")
assert runtime.health.summary()["last_good_step"] is not None
runtime.wait_for_everyone()

# Both hosts contributed shard files; the index lists them.
step_dir = os.path.join(ckpt_dir, "4", "model_0")
assert os.path.exists(os.path.join(step_dir, f"shard_p{rank}.npz")), os.listdir(step_dir)
if rank == 0:
    assert os.path.exists(os.path.join(step_dir, "index.json"))

# Cross-process RESUME: a fresh tree restores the sharded checkpoint — each
# host reads only the chunks its addressable shards need (plus the other
# host's file for resharded regions) and lands on the saved step.
model2 = MLP(in_features=8, num_classes=4, hidden=(16,))
module2 = rt.Module(
    model2,
    capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.adam(), learning_rate=1e-2)],
)
tree2 = rt.Launcher(
    [
        rt.Looper(
            [
                rt.Dataset(data, batch_size=32),
                module2,
                rt.Checkpointer(
                    output_dir=ckpt_dir, save_every=1000,
                    resume_from=os.path.join(ckpt_dir, "4"),
                    resume_capsules=False,
                ),
            ],
            tag="train",
            progress=False,
        )
    ],
    num_epochs=1,
    runtime=runtime,
)
attrs = rt.Attributes()
tree2.setup(attrs)
import numpy as _np
assert int(_np.asarray(module2.state["step"])) == 4, module2.state["step"]
assert module2._prepared.host_step == 4
tree2.destroy(attrs)
runtime.wait_for_everyone()

# Meter(gather_on="main") across processes: every rank participates in the
# gather collectives (no hang), but only rank 0 retains the global batch
# and accumulates host-path metrics.
from rocket_tpu.core.meter import Metric

class CountSamples(Metric):
    def __init__(self):
        super().__init__()
        self.total = 0

    def launch(self, attrs=None):
        self.total += int(attrs.batch["label"].shape[0])

    def reset(self, attrs=None):
        pass

counter = CountSamples()
model3 = MLP(in_features=8, num_classes=4, hidden=(16,))
rt.Launcher(
    [
        rt.Looper(
            [
                rt.Dataset(data, batch_size=32),
                rt.Module(model3),
                rt.Meter(["logits", "label"], [counter], gather_on="main"),
            ],
            tag="val",
            grad_enabled=False,
            progress=False,
        )
    ],
    num_epochs=1,
    runtime=runtime,
).launch()
expected = 128 if rank == 0 else 0
assert counter.total == expected, (rank, counter.total)
runtime.wait_for_everyone()
print(f"RANK{rank} OK", flush=True)
"""


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_distributed_train_and_checkpoint(tmp_path):
    _require_multiprocess_backend()
    port = _free_port()
    procs = []
    for rank in range(2):
        env = dict(os.environ)
        env.update(
            JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
            JAX_NUM_PROCESSES="2",
            JAX_PROCESS_ID=str(rank),
            XLA_FLAGS="--xla_force_host_platform_device_count=2",
            JAX_PLATFORMS="cpu",
            REPO_ROOT=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            OUT=str(tmp_path),
        )
        # A worker must not inherit a single-process test runtime.
        env.pop("JAX_PLATFORM_NAME", None)
        procs.append(
            subprocess.Popen(
                [sys.executable, "-c", _WORKER],
                env=env,
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
        )
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        outs.append(out)
        assert proc.returncode == 0, f"rank {rank} failed:\n{out}"
        assert f"RANK{rank} OK" in out, out


# ---------------------------------------------------------------------------
# Elastic restore across process counts (round-4 verdict ask #5): save under
# 2 real jax.distributed processes, restore under 1 and under 4 (the
# resharding reader rebuilds each leaf from whatever chunk files exist),
# verify bitwise state equality against a rank-0 reference dump, and train on.
# ---------------------------------------------------------------------------

_ELASTIC_COMMON = r"""
import os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO_ROOT"])

import numpy as np
import optax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.runtime.context import Runtime

def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()

def make_data():
    rng = np.random.default_rng(0)
    return [
        {"image": rng.normal(size=8).astype(np.float32),
         "label": np.int32(i % 4)}
        for i in range(128)
    ]

def build_tree(runtime, ckpt_dir, resume_from=None):
    module = rt.Module(
        MLP(in_features=8, num_classes=4, hidden=(16,)),
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    # save_every=2 -> a MID-epoch checkpoint at step 2 (of the 4-batch
    # epoch): restoring it leaves batches to train, so the continuation
    # leg actually advances.
    tree = rt.Launcher(
        [rt.Looper(
            [rt.Dataset(make_data(), batch_size=32, device_cache=False),
             module,
             rt.Checkpointer(output_dir=ckpt_dir, save_every=2,
                             resume_from=resume_from)],
            tag="train", progress=False)],
        num_epochs=1, runtime=runtime,
    )
    return tree, module

def flat_state(module):
    # Full host values keyed like the checkpoint index: every leaf is
    # replicated over the data mesh, so addressable shard 0 IS the global
    # array on any process count.
    from rocket_tpu.utils.pytree import key_path_str
    out = {}
    for kp, leaf in jax.tree_util.tree_flatten_with_path(
            {"params": module.state["params"],
             "opt_state": module.state["opt_state"],
             "step": module.state["step"]})[0]:
        out[key_path_str(kp)] = np.asarray(leaf.addressable_data(0))
    return out

def run_one_epoch(tree, attrs):
    # The Launcher.launch epoch body, without its closing destroy (state
    # must stay inspectable after the run).
    from rocket_tpu.core.capsule import Events
    attrs.launcher = rt.Attributes(epoch_idx=0, num_epochs=1)
    for capsule in tree.capsules:
        capsule.dispatch(Events.SET, attrs)
        capsule.dispatch(Events.LAUNCH, attrs)
        capsule.dispatch(Events.RESET, attrs)
"""

_ELASTIC_SAVER = _ELASTIC_COMMON + r"""
runtime = Runtime(mesh_shape={"data": 4}, seed=0, project_dir=os.environ["OUT"])
assert jax.process_count() == 2
ckpt_dir = os.path.join(os.environ["OUT"], "ckpts")
tree, module = build_tree(runtime, ckpt_dir)
tree.launch()
assert os.path.isdir(os.path.join(ckpt_dir, "2")), os.listdir(ckpt_dir)
print(f"RANK{runtime.process_index} SAVED", flush=True)
"""

_ELASTIC_RESTORER = _ELASTIC_COMMON + r"""
runtime = Runtime(mesh_shape={"data": 4}, seed=0, project_dir=os.environ["OUT"])
nproc = jax.process_count()  # AFTER Runtime: process_count() inits the backend
ckpt_dir = os.path.join(os.environ["OUT"], "ckpts")
ckpt = os.path.join(ckpt_dir, "2")
tree, module = build_tree(runtime, ckpt_dir, resume_from=ckpt)
attrs = rt.Attributes()
tree.setup(attrs)

# The canonical reference is the checkpoint FILE itself (template-free
# read -> flat host numpy). The resharding restore on this topology must
# reproduce it bitwise.
from rocket_tpu.runtime import checkpoint_io
ref = checkpoint_io.load_pytree(os.path.join(ckpt, "model_0"))
got = flat_state(module)
assert set(got) <= set(ref), (sorted(got), sorted(ref))
for name in got:
    np.testing.assert_array_equal(
        np.asarray(ref[name]), got[name], err_msg=name)
assert int(np.asarray(module.state["step"])) == 2

# Training continues mid-epoch from the restored state on THIS topology:
# the loader fast-forwards the 2 consumed batches and trains the rest.
run_one_epoch(tree, attrs)
assert int(np.asarray(module.state["step"])) == 4
after = flat_state(module)
np.savez(os.path.join(os.environ["OUT"], f"after_{nproc}.npz"), **after)
tree.destroy(attrs)
runtime.wait_for_everyone()
print(f"RANK{runtime.process_index} RESTORED{nproc} OK", flush=True)
"""


def _spawn_group(nproc, devices_per_proc, script, tmp_path, distributed):
    port = _free_port()
    procs = []
    for rank in range(nproc):
        env = dict(os.environ)
        env.update(
            XLA_FLAGS=f"--xla_force_host_platform_device_count={devices_per_proc}",
            JAX_PLATFORMS="cpu",
            REPO_ROOT=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            OUT=str(tmp_path),
        )
        if distributed:
            env.update(
                JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{port}",
                JAX_NUM_PROCESSES=str(nproc),
                JAX_PROCESS_ID=str(rank),
            )
        else:
            for k in ("JAX_COORDINATOR_ADDRESS", "JAX_NUM_PROCESSES",
                      "JAX_PROCESS_ID"):
                env.pop(k, None)
        env.pop("JAX_PLATFORM_NAME", None)
        procs.append(subprocess.Popen(
            [sys.executable, "-c", script], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        ))
    outs = []
    for rank, proc in enumerate(procs):
        try:
            out, _ = proc.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for p in procs:
                p.kill()
            raise
        outs.append(out)
        assert proc.returncode == 0, f"rank {rank} failed:\n{out}"
    return outs


_EMERGENCY_SAVER = _ELASTIC_COMMON + r"""
runtime = Runtime(mesh_shape={"data": 4}, seed=0, project_dir=os.environ["OUT"])
nproc = jax.process_count()
ckpt_dir = os.path.join(os.environ["OUT"], "ckpts")
tree, module = build_tree(runtime, ckpt_dir)
attrs = rt.Attributes()
tree.setup(attrs)
run_one_epoch(tree, attrs)

# The emergency path itself — synchronous, collective-free, every rank
# writing its own chunks into ONE bundle dir (the drain-save layout).
from rocket_tpu.core.checkpoint import Checkpointer
ckpt = tree.find(Checkpointer)[0]
em = os.path.join(os.environ["OUT"], "emergency")
ckpt.save_emergency(em, include_capsules=True)
runtime.wait_for_everyone()  # all ranks' shards durable before anyone exits
tree.destroy(attrs)
print(f"RANK{runtime.process_index} EMSAVED{nproc}", flush=True)
"""

_EMERGENCY_RESTORER = _ELASTIC_COMMON + r"""
runtime = Runtime(mesh_shape={"data": 4}, seed=0, project_dir=os.environ["OUT"])
nproc = jax.process_count()
ckpt_dir = os.path.join(os.environ["OUT"], "ckpts_resume")
em = os.path.join(os.environ["OUT"], "emergency")
tree, module = build_tree(runtime, ckpt_dir, resume_from=em)
attrs = rt.Attributes()
tree.setup(attrs)

# The canonical reference is the emergency bundle ITSELF (template-free
# read -> flat host numpy); the resharding restore on THIS topology must
# reproduce it bitwise.
from rocket_tpu.runtime import checkpoint_io
ref = checkpoint_io.load_pytree(os.path.join(em, "model_0"))
got = flat_state(module)
assert set(got) <= set(ref), (sorted(got), sorted(ref))
for name in got:
    np.testing.assert_array_equal(
        np.asarray(ref[name]), got[name], err_msg=name)
assert int(np.asarray(module.state["step"])) == 4
tree.destroy(attrs)
runtime.wait_for_everyone()
print(f"RANK{runtime.process_index} EMRESTORED{nproc} OK", flush=True)
"""


@pytest.mark.slow
def test_emergency_bundle_restores_across_process_counts(tmp_path):
    """ISSUE 9 satellite: the elastic-restore claim, proven on the
    EMERGENCY bundle specifically — save_emergency under 2 real
    jax.distributed processes, restore under 1 (and vice versa) through
    the resharding reader, bitwise-equal to the bundle's own chunks.
    This is the drain checkpoint's exact write path."""
    _require_multiprocess_backend()

    # 2-process save -> 1-process restore.
    outs = _spawn_group(2, 2, _EMERGENCY_SAVER, tmp_path, distributed=True)
    assert any("RANK0 EMSAVED2" in o for o in outs)
    outs = _spawn_group(1, 4, _EMERGENCY_RESTORER, tmp_path,
                        distributed=False)
    assert any("EMRESTORED1 OK" in o for o in outs)

    # 1-process save -> 2-process restore (the other direction).
    reverse = tmp_path / "reverse"
    reverse.mkdir()
    outs = _spawn_group(1, 4, _EMERGENCY_SAVER, reverse, distributed=False)
    assert any("EMSAVED1" in o for o in outs)
    outs = _spawn_group(2, 2, _EMERGENCY_RESTORER, reverse, distributed=True)
    assert any("EMRESTORED2 OK" in o for o in outs)


@pytest.mark.slow
def test_elastic_checkpoint_restore_across_process_counts(tmp_path):
    """Save under 2 processes; restore (and keep training) under 1 AND
    under 4. The resharding reader must rebuild identical state from the
    2-host shard files on every topology, and the 4-process leg doubles
    as the >2-process smoke test."""
    _require_multiprocess_backend()
    import numpy as np

    outs = _spawn_group(2, 2, _ELASTIC_SAVER, tmp_path, distributed=True)
    assert any("RANK0 SAVED" in o for o in outs)

    # Restore under ONE process (4 local virtual devices, no coordinator).
    outs = _spawn_group(1, 4, _ELASTIC_RESTORER, tmp_path, distributed=False)
    assert any("RANK0 RESTORED1 OK" in o for o in outs)

    # Restore under FOUR processes (1 device each -> same 4-device mesh).
    outs = _spawn_group(4, 1, _ELASTIC_RESTORER, tmp_path, distributed=True)
    assert any("RANK0 RESTORED4 OK" in o for o in outs)

    # The continued step's result agrees across topologies: same global
    # batch, same restored state — only the collective reduction order
    # differs, so tight allclose rather than bitwise.
    a1 = dict(np.load(tmp_path / "after_1.npz"))
    a4 = dict(np.load(tmp_path / "after_4.npz"))
    assert set(a1) == set(a4)
    for name in a1:
        np.testing.assert_allclose(
            a1[name], a4[name], rtol=1e-5, atol=1e-6, err_msg=name)
