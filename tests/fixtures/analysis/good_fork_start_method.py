"""RKT107 clean negative: fork-free process creation."""
import multiprocessing


def make_pool(start_method=None):
    if start_method is None:
        methods = multiprocessing.get_all_start_methods()
        start_method = "forkserver" if "forkserver" in methods else "spawn"
    return multiprocessing.get_context(start_method)
