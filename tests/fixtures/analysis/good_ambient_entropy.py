"""RKT113 clean negatives: explicit seeds/keys; host telemetry stays host."""
import time

import jax
import jax.numpy as jnp


@jax.jit
def keyed_step(x, key):
    noise = jax.random.normal(key, x.shape)  # keyed RNG, reproducible
    return x + noise


def timed_host_loop(step_fn, x, key):
    # Host-side telemetry timestamps never enter the traced program.
    started = time.time()
    y = step_fn(x, key)
    return y, time.time() - started
