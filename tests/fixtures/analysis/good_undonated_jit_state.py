"""Clean counterparts for RKT111: donated state threads, and an eval
transform that returns a value (not a successor state) and so is not a
threading loop at all."""

from functools import partial

import jax


def train_step(state, batch):
    new_params = jax.tree.map(lambda p: p - 0.1, state["params"])
    return {"params": new_params}, batch.sum()


# Donated call form: the update happens in place.
step = jax.jit(train_step, donate_argnums=(0,))


# Donated decorator form.
@partial(jax.jit, donate_argnums=(0,))
def opt_update(opt_state, grads):
    mu = jax.tree.map(lambda m, g: 0.9 * m + g, opt_state["mu"], grads)
    return {"mu": mu}, grads


def eval_step(params, batch):
    logits = batch @ params["w"]
    return logits


# An eval transform returns logits, not a successor state — no donation
# expected, no finding.
evaluate = jax.jit(eval_step)
