"""RKT102 clean negative: per-step effects via jax primitives."""
import jax
import jax.numpy as jnp


@jax.jit
def quiet_step(x, key):
    noise = jax.random.normal(key, ())  # keyed RNG, fresh per step
    return x + noise


def log_outside(x):
    print("host-side logging outside the traced region is fine:", x)
    return jnp.asarray(x)
