"""RKT109 clean negatives: lock discipline held (or no lock owned)."""

import threading


class DisciplinedRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}     # construction happens-before sharing
        self._events = []
        self._local = threading.local()

    def bump(self, name):
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + 1

    def drain(self):
        with self._lock:
            out = list(self._events)
            self._events.clear()
        return out

    def _merge_locked(self, other):
        # *_locked convention: the caller holds the lock.
        self._counts.update(other)

    def scratch(self, item):
        # threading.local attributes are thread-isolated by construction.
        stack = getattr(self._local, "stack", None)
        if stack is None:
            stack = self._local.stack = []
        stack.append(item)

    def manual(self, name):
        self._lock.acquire()
        try:
            self._counts[name] = 0
        finally:
            self._lock.release()


class SingleThreaded:
    """No lock owned: single-threaded by design, rule does not apply."""

    def __init__(self):
        self.items = []

    def add(self, item):
        self.items.append(item)
