"""RKT112 clean negatives: sorted() pins every order before it matters."""
import jax
import jax.numpy as jnp


def assemble_params(shapes):
    leaves = []
    for name in sorted({"wte", "wpe", "head"}):  # sorted set: stable
        leaves.append((name, jnp.zeros(shapes[name])))
    return dict(leaves)


def dedup_rules(patterns):
    return sorted(set(patterns))  # sorted dedup: stable


@jax.jit
def step(x, scale_by):
    total = x
    for key in sorted(set(scale_by)):  # sorted before the trace sees it
        total = total * scale_by[key]
    return total


def insertion_ordered(config):
    # dict displays / dicts iterate in insertion order — deterministic.
    for key in {"a": 1, "b": 2}:
        config.setdefault(key, 0)
    return config
