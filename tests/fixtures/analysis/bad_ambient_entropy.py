"""RKT113 true positives: ambient entropy baked into a traced step."""
import os
import time

import jax
import jax.numpy as jnp


@jax.jit
def stamped_step(x):
    started = time.time()  # BAD: the clock is a trace-time constant
    return x + jnp.float32(started)


@jax.jit
def salted_step(x, table):
    salt = hash("step-salt")  # BAD: PYTHONHASHSEED randomizes this
    seed = os.urandom(4)  # BAD: fresh entropy every build
    return x * jnp.float32(salt % 1024) + jnp.float32(len(seed))
