"""RKT103 clean negative: the loop stays async; one batched read after."""
import jax


def drive(step, state, batches):
    losses = []
    for batch in batches:
        state, loss = step(state, batch)
        losses.append(loss)  # lazy device scalar, no sync
    return jax.device_get(losses)  # one batched transfer past the loop
