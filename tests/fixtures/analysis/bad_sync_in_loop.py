"""RKT103 true positive: device sync inside the iteration loop."""
import jax


def drive(step, state, batches):
    losses = []
    for batch in batches:
        state, loss = step(state, batch)
        losses.append(jax.device_get(loss))  # BAD: D2H sync per iteration
        jax.block_until_ready(state)  # BAD: serializes host and device
    return losses
