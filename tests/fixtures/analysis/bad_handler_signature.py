"""RKT105 true positive: handlers dispatch() cannot call as handler(attrs)."""
from rocket_tpu.core.capsule import Capsule


class WrongArity(Capsule):
    def launch(self):  # BAD: no slot for attrs
        pass

    def reset(self, attrs, extra):  # BAD: a second REQUIRED param
        pass
