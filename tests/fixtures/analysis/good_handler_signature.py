"""RKT105 clean negative: the (self, attrs) contract, plus non-handler
methods with free signatures."""
from rocket_tpu.core.capsule import Capsule


class WellFormed(Capsule):
    def launch(self, attrs=None):
        pass

    def reset(self, attrs=None, verbose=False):  # extra DEFAULTED param ok
        pass

    def set(self, *args):  # attrs lands in *args: callable
        pass

    def helper(self, a, b, *args, **kwargs):  # not a lifecycle hook
        return a, b, args, kwargs
