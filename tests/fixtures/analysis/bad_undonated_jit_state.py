"""Seeded RKT111 violations: jit-wrapped steps threading recurrent
state with no donation — the old state stays live while the new one is
written, a transient 2x copy every call."""

import jax


def train_step(state, batch):
    new_params = jax.tree.map(lambda p: p - 0.1, state["params"])
    new_state = {"params": new_params, "step": state["step"] + 1}
    return new_state, batch.sum()


# Violation 1 (call form): the canonical train loop wiring, minus the
# donate_argnums that makes the update in-place.
step = jax.jit(train_step)


# Violation 2 (decorator form): an optimizer update threading its
# moment tree through a bare @jax.jit.
@jax.jit
def opt_update(opt_state, grads):
    mu = jax.tree.map(lambda m, g: 0.9 * m + g, opt_state["mu"], grads)
    out = {"mu": mu, "count": opt_state["count"] + 1}
    return out, grads
