"""RKT110 true positives: broad except-and-continue inside retry loops."""

import time


def supervise_forever(run_once):
    # Bare except in a supervision loop: Ctrl-C and SystemExit (the
    # graceful-drain exit) are swallowed and the loop spins on.
    while True:
        try:
            run_once()
        except:  # noqa: E722 — the fixture plants exactly this hazard
            time.sleep(1.0)


def retry_with_base_exception(fn):
    # BaseException without re-raise: same swallow, spelled explicitly.
    for _attempt in range(5):
        try:
            return fn()
        except BaseException:
            continue
    return None


def eats_keyboard_interrupt(jobs):
    # Naming the interrupt directly and falling through is no better.
    for job in jobs:
        try:
            job()
        except (ValueError, KeyboardInterrupt):
            pass


def nested_break_is_not_terminal(fn, cleanups):
    # The break belongs to the INNER for loop: the outer supervision loop
    # still swallows the interrupt and continues iterating.
    while True:
        try:
            fn()
        except BaseException:
            for cleanup in cleanups:
                cleanup()
                break


def nested_return_is_not_terminal(fn, on_error):
    # The return sits in a nested function — it leaves the callback, not
    # this loop; the handler itself falls through and spins on.
    while True:
        try:
            fn()
        except BaseException:
            def callback():
                return "handled"
            on_error(callback)
