"""RKT104 clean negative: overrides chain to the base hook."""
from rocket_tpu.core.capsule import Capsule
from rocket_tpu.core.dispatcher import Dispatcher


class TidyCapsule(Capsule):
    def setup(self, attrs=None):
        super().setup(attrs)
        self.resource = object()

    def destroy(self, attrs=None):
        self.resource = None
        super().destroy(attrs)


class ExplicitBase(Dispatcher):
    def setup(self, attrs=None):
        # The explicit-base spelling (Launcher's idiom) also counts.
        Dispatcher.setup(self, attrs)
