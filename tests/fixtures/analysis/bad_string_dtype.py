"""True positives for RKT108: string-literal dtypes in casts."""

import jax.numpy as jnp
import numpy as np


def host_logits(logits):
    return np.asarray(logits).astype("float32")  # RKT108


def upcast_loss(nll):
    return nll.astype("float64").sum()  # RKT108


def narrow_activations(x):
    return x.astype("bfloat16")  # RKT108


def keyword_form(x):
    return x.astype(dtype="float32")  # RKT108 — keyword spelling too


def dynamic_name(x):
    # A COMPUTED string is still a string dtype at runtime but not a
    # literal — out of scope for a syntactic rule (and rare enough that
    # the literal form is the one worth policing).
    return x.astype(jnp.float32)
