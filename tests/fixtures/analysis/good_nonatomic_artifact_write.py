"""Fixture: RKT114 must stay quiet — temp-then-rename commits, reads,
and non-JSON writes."""

import json
import os


def save_state(state, path):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(state, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def load_state(path):
    with open(path) as f:
        return json.load(f)


def append_log_line(path, line):
    with open(path, "a") as f:
        f.write(line + "\n")
