"""RKT112 true positives: hash-order iteration reaching the trace."""
import jax
import jax.numpy as jnp


def assemble_params(shapes):
    leaves = []
    for name in {"wte", "wpe", "head"}:  # BAD: set iterated unsorted
        leaves.append((name, jnp.zeros(shapes[name])))
    return dict(leaves)


def dedup_rules(patterns):
    return list(set(patterns))  # BAD: list(set(...)) keeps unstable order


@jax.jit
def step(x, scale_by):
    total = x
    keys = set(scale_by)
    for key in keys:  # BAD: inferred set var iterated inside jit
        total = total * scale_by[key]
    return total
