"""RKT110 clean negatives: disciplined exception handling around loops."""


def supervise_forever(run_once, backoff):
    # Catching Exception is the correct "retry on any failure" spelling:
    # KeyboardInterrupt/SystemExit still propagate and stop the loop.
    while True:
        try:
            run_once()
        except Exception:
            backoff()


def reraise_is_terminal(fn, cleanup):
    # A broad catch that RE-RAISES leaves nothing swallowed.
    for _attempt in range(5):
        try:
            return fn()
        except BaseException:
            cleanup()
            raise
    return None


def break_is_terminal(fn):
    # Leaving the loop on interrupt is the cooperative-shutdown idiom.
    while True:
        try:
            fn()
        except KeyboardInterrupt:
            break


def break_after_inner_loop_is_terminal(fn, cleanups):
    # The inner loop runs to completion, then the handler's OWN break
    # leaves the supervision loop — terminal.
    while True:
        try:
            fn()
        except BaseException:
            for cleanup in cleanups:
                cleanup()
            break


def outside_any_loop(fn, fallback):
    # Not a retry loop: a one-shot cleanup try at function level is out
    # of scope for this rule (ruff's E722 still has opinions on bare
    # except; this fixture uses BaseException deliberately).
    try:
        return fn()
    except BaseException:
        return fallback
