"""Fixture: RKT114 must fire — JSON artifacts serialized in place."""

import json


def save_state(state, path):
    with open(path, "w") as f:
        json.dump(state, f)  # no os.replace anywhere in this function


def save_report(report, path):
    handle = open(path, "w", encoding="utf-8")
    handle.write(json.dumps(report, indent=2))
    handle.close()
