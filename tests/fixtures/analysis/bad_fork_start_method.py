"""RKT107 true positive: forking a (potentially multithreaded) JAX parent."""
import multiprocessing
import os


def make_pool():
    ctx = multiprocessing.get_context("fork")  # BAD
    return ctx


def spawn_child():
    pid = os.fork()  # BAD
    return pid
