"""RKT104 true positive: lifecycle overrides that drop the base call."""
from rocket_tpu.core.capsule import Capsule


class LeakyCapsule(Capsule):
    def setup(self, attrs=None):
        self.resource = object()  # BAD: never registers with the runtime

    def destroy(self, attrs=None):
        self.resource = None  # BAD: never unwinds the checkpoint stack
