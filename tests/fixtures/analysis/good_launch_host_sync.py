"""RKT106 clean negative: lazy device accumulation; materialize in
reset(), the epoch boundary."""
import numpy as np

from rocket_tpu.core.capsule import Capsule


class LazyMetric(Capsule):
    def launch(self, attrs=None):
        value = attrs.step_metrics.loss
        self.total = getattr(self, "total", 0.0) + value  # lazy jnp add

    def reset(self, attrs=None):
        self.value = float(np.asarray(self.total))  # once per epoch
        self.total = 0.0
