"""RKT109 true positives: a lock-owning class mutating shared state
outside the lock."""

import threading


class LeakyRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counts = {}
        self._events = []
        self.total = 0

    def bump(self, name):
        # Plain dict item assignment without the lock.
        self._counts[name] = self._counts.get(name, 0) + 1

    def note(self, event):
        # Container mutator without the lock.
        self._events.append(event)

    def accumulate(self, n):
        # Augmented assignment without the lock.
        self.total += n

    def trim(self):
        # del on shared state without the lock.
        del self._events[:-10]

    def locked_ok(self, name):
        with self._lock:
            self._counts[name] = 0
