"""RKT101 true positive: tracer forced to host inside a jit region."""
import jax
import jax.numpy as jnp
import numpy as np


def train_step(state, batch):
    loss = jnp.mean(batch["x"] ** 2)
    scale = float(loss)  # BAD: concretizes the tracer
    host = np.asarray(loss)  # BAD: materializes the tracer on host
    return state, loss * scale + host.sum()


step = jax.jit(train_step, donate_argnums=(0,))
