"""Clean negatives for RKT108: canonical dtype objects in casts."""

import jax.numpy as jnp
import numpy as np


def host_logits(logits):
    return np.asarray(logits).astype(np.float32)


def upcast_loss(nll):
    return nll.astype(jnp.float32).sum()


def narrow_activations(x, compute_dtype=jnp.bfloat16):
    return x.astype(compute_dtype)


def match_peer(x, y):
    # Casting to another array's dtype is the cast-at-use convention
    # itself — never a string.
    return x.astype(y.dtype)
