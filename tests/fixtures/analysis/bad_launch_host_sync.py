"""RKT106 true positive: per-iteration D2H sync in a capsule launch."""
import numpy as np

from rocket_tpu.core.capsule import Capsule


class SyncingMetric(Capsule):
    def launch(self, attrs=None):
        value = attrs.step_metrics.loss
        self.total = getattr(self, "total", 0.0) + float(value)  # BAD
        self.history = np.asarray(value)  # BAD: per-step materialization
