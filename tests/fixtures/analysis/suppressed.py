"""Suppression fixtures: the same hazards as the bad files, annotated.

# rocketlint: disable-file=RKT103
"""
import jax


def drive(step, state, batches):
    losses = []
    for batch in batches:
        state, loss = step(state, batch)
        # File-wide directive above silences RKT103 for both sync calls.
        losses.append(jax.device_get(loss))
        jax.block_until_ready(state)
    return losses


def train_step(state, batch):
    scale = float(batch["scale"])  # rocketlint: disable=RKT101 — static per epoch
    return state, scale


step = jax.jit(train_step, donate_argnums=(0,))
