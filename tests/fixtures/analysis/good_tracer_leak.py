"""RKT101 clean negative: symbolic math in the jit region, host math
outside it."""
import jax
import jax.numpy as jnp


def train_step(state, batch):
    loss = jnp.mean(batch["x"] ** 2)
    scale = jnp.sqrt(loss)  # stays symbolic
    return state, loss * scale


step = jax.jit(train_step, donate_argnums=(0,))


def report(metrics):
    # Host conversion OUTSIDE the traced region is fine.
    return float(metrics)
