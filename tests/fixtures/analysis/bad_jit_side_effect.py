"""RKT102 true positive: trace-time side effects inside a jit region."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def noisy_step(x):
    print("step!")  # BAD: prints once, at trace time
    noise = np.random.normal(size=())  # BAD: a constant after trace
    return x + jnp.float32(noise)
