"""rocket_tpu.resilience: fault plans, drain protocol, supervisor loop.

Fast tier: fault-plan parsing/determinism, injector hooks with injected
action fns, the in-process Looper drain path (SIGTERM semantics without a
process spawn: request the drain programmatically, assert the
GracefulDrain SystemExit, the drain checkpoint on disk, and the resumed
run completing), supervisor control flow with a scripted generation
runner (restart budget, crash-loop refusal, elastic degradation, drain
honoring, goodput accounting), and the watchdog-escalation exit wiring.
The process-spawning legs live in scripts/resilience_smoke.py (CI) and
the slow-tier launch/multiprocess tests.
"""

import json
import os
import signal

import jax
import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.resilience import (
    EXIT_DRAINED,
    EXIT_WEDGED,
    DrainState,
    Fault,
    FaultInjector,
    FaultPlan,
    GracefulDrain,
    RestartPolicy,
    Supervisor,
    install_signal_drain,
    is_complete_checkpoint,
    newest_complete_step,
)
from rocket_tpu.runtime.context import Runtime


def cross_entropy(batch):
    import optax

    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def class_data(n=128):
    rng = np.random.default_rng(0)
    return [
        {"image": rng.normal(size=8).astype(np.float32),
         "label": np.int32(i % 4)}
        for i in range(n)
    ]


# -- FaultPlan ---------------------------------------------------------------


def test_fault_plan_parse_roundtrip():
    spec = "kill:step=23;sigterm:wall=3.5;wedge:step=7,secs=600;poison:step=3,rank=1,gen=1"
    plan = FaultPlan.parse(spec)
    assert [f.kind for f in plan] == ["kill", "sigterm", "wedge", "poison"]
    assert plan.faults[0].step == 23 and plan.faults[0].gen == 0
    assert plan.faults[1].wall == 3.5
    assert plan.faults[2].secs == 600.0
    assert plan.faults[3] == Fault("poison", step=3, rank=1, gen=1)
    # The wire format round-trips through parse(to_spec()).
    again = FaultPlan.parse(plan.to_spec())
    assert again.faults == plan.faults


@pytest.mark.parametrize("bad", [
    "frobnicate:step=1",          # unknown kind
    "kill:when=now",              # unknown key
    "kill:gen=0",                 # kill needs step=
    "sigterm:rank=1",             # sigterm needs step= or wall=
    "kill:step",                  # malformed item
])
def test_fault_plan_strict_parse(bad):
    with pytest.raises(ValueError):
        FaultPlan.parse(bad)


def test_fault_plan_sample_is_deterministic():
    a = FaultPlan.sample(seed=7, max_step=50, nproc=4, n=3)
    b = FaultPlan.sample(seed=7, max_step=50, nproc=4, n=3)
    assert a.faults == b.faults
    assert FaultPlan.sample(seed=8, max_step=50, nproc=4, n=3).faults != a.faults
    for fault in a:
        assert 1 <= fault.step < 50
        assert fault.rank is None or 0 <= fault.rank < 4


def test_injector_scopes_by_generation_and_rank():
    plan = FaultPlan.parse("kill:step=2,rank=1;sigterm:step=5,gen=1")
    # Rank 0, generation 0: nothing matches.
    inj = FaultInjector(plan, process_index=0, generation=0,
                        kill_fn=lambda: None)
    assert inj.active == []
    # Rank 1, generation 0: only the kill.
    inj = FaultInjector(plan, process_index=1, generation=0,
                        kill_fn=lambda: None)
    assert [f.kind for f in inj.active] == ["kill"]
    # Generation 1 (the restart): only the gen=1 sigterm — a restarted
    # generation is not re-killed by generation-0 faults.
    inj = FaultInjector(plan, process_index=1, generation=1,
                        kill_fn=lambda: None)
    assert [f.kind for f in inj.active] == ["sigterm"]


def test_injector_from_env(monkeypatch):
    assert FaultInjector.from_env(environ={}) is None
    env = {"ROCKET_TPU_FAULTS": "kill:step=4", "ROCKET_TPU_GENERATION": "2"}
    inj = FaultInjector.from_env(environ=env)
    assert inj is not None and inj.generation == 2
    assert inj.active == []  # the fault is gen=0, we are gen 2


def test_injector_step_hook_fires_at_step():
    fired = []
    plan = FaultPlan.parse("kill:step=3")
    inj = FaultInjector(plan, kill_fn=lambda: fired.append("kill"))
    for i in range(5):
        inj.step_hook("train", i)
    assert fired == ["kill"]
    assert inj.fired == ("kill@train[2]",)


def test_injector_wedge_sleeps():
    slept = []
    plan = FaultPlan.parse("wedge:step=2,secs=123")
    inj = FaultInjector(plan, sleep_fn=slept.append)
    inj.step_hook("train", 0)
    inj.step_hook("train", 1)
    assert slept == [123.0]


def test_injector_poison_hook_nans_exactly_one_batch():
    plan = FaultPlan.parse("poison:step=2")
    inj = FaultInjector(plan)
    batch = {"image": np.ones((4, 8), np.float32), "label": np.arange(4)}
    first = inj.poison_hook(batch)
    assert np.isfinite(first["image"]).all()
    second = inj.poison_hook(batch)
    assert np.isnan(second["image"]).all()
    # Integer leaves pass through untouched (NaN has no int encoding).
    assert (second["label"] == batch["label"]).all()
    third = inj.poison_hook(batch)
    assert np.isfinite(third["image"]).all()


def test_injector_poison_hook_poisons_device_resident_batches():
    """A DeviceCachedLoader (the default device_cache="auto" path for
    small datasets) yields jax Arrays, not np.ndarrays — the poison must
    still land (duck-typed dtype/shape match), as a host NaN array the
    step places like any other input."""
    import jax.numpy as jnp

    plan = FaultPlan.parse("poison:step=1")
    inj = FaultInjector(plan)
    batch = {"image": jnp.ones((4, 8), jnp.float32),
             "label": jnp.arange(4)}
    out = inj.poison_hook(batch)
    assert np.isnan(np.asarray(out["image"])).all()
    assert (np.asarray(out["label"]) == np.arange(4)).all()
    assert inj.fired == ("poison@batch[1]",)


def test_injector_poison_hook_marker_batch_is_not_counted_as_fired():
    """Fused device-gather MARKER batches share their cache leaf across
    every step — NaN-filling it would poison the whole rest of the run,
    so the hook must pass the batch through untouched AND must not record
    the fault as fired (a silently no-op fault reads as a vacuously
    passing test)."""
    plan = FaultPlan.parse("poison:step=1")
    inj = FaultInjector(plan)
    cache = np.ones((16, 8), np.float32)
    batch = {"_device_gather": {"cache": {"image": cache},
                                "perm": np.arange(16), "index": 0}}
    out = inj.poison_hook(batch)
    assert out is batch
    assert np.isfinite(cache).all()
    assert inj.fired == ()


# -- drain protocol ----------------------------------------------------------


def test_graceful_drain_is_systemexit_with_drained_code():
    exc = GracefulDrain(checkpoint="/tmp/x", reason="SIGTERM")
    assert isinstance(exc, SystemExit)
    assert exc.code == EXIT_DRAINED
    assert exc.checkpoint == "/tmp/x"
    # NOT an Exception: the Looper's crash-forensics handler must not
    # treat a drain as a failure.
    assert not isinstance(exc, Exception)


def test_drain_state_latches_first_request():
    drain = DrainState()
    assert not drain.requested
    drain.request("SIGTERM")
    drain.request("later")
    assert drain.requested and drain.reason == "SIGTERM"
    assert drain.requested_at is not None


def test_install_signal_drain_routes_sigterm():
    drain = DrainState()
    previous = signal.getsignal(signal.SIGTERM)
    previous_int = signal.getsignal(signal.SIGINT)
    try:
        assert install_signal_drain(drain)
        os.kill(os.getpid(), signal.SIGTERM)
        assert drain.requested and drain.reason == "SIGTERM"
    finally:
        signal.signal(signal.SIGTERM, previous)
        signal.signal(signal.SIGINT, previous_int)


def test_install_signal_drain_routes_first_sigint_then_restores():
    """An interactive Ctrl-C reaches the whole foreground process group:
    the first SIGINT must drain (not die mid-orchestration with a
    KeyboardInterrupt), and the handler must restore the previous SIGINT
    disposition so a second Ctrl-C interrupts hard."""
    drain = DrainState()
    previous = signal.getsignal(signal.SIGTERM)
    previous_int = signal.getsignal(signal.SIGINT)
    try:
        assert install_signal_drain(drain)
        assert signal.getsignal(signal.SIGINT) is not previous_int
        os.kill(os.getpid(), signal.SIGINT)
        assert drain.requested and drain.reason == "SIGINT"
        assert signal.getsignal(signal.SIGINT) is previous_int
    finally:
        signal.signal(signal.SIGTERM, previous)
        signal.signal(signal.SIGINT, previous_int)


class DrainAt(rt.Capsule):
    """Requests a drain after N completed waves (the programmatic stand-in
    for a SIGTERM landing mid-run)."""

    def __init__(self, after):
        super().__init__(priority=500)
        self._after = after
        self._seen = 0

    def launch(self, attrs=None):
        self._seen += 1
        if self._seen == self._after:
            self._runtime.drain.request("test-preemption")


class GrabState(rt.Capsule):
    """Mirrors the module's latest step/params so they stay inspectable
    after DESTROY tears the tree down."""

    def __init__(self, module):
        super().__init__(priority=10)
        self._module = module
        self.step = None
        self.params = None

    def launch(self, attrs=None):
        if self._module.state is not None:
            self.step = self._module.state["step"]
            self.params = self._module.state["params"]


def _tree(runtime, ckpt_dir, drain_after=None, save_every=1000,
          num_epochs=2, keep_last=None):
    module = rt.Module(
        MLP(in_features=8, num_classes=4, hidden=(16,)),
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    grab = GrabState(module)
    capsules = [
        rt.Dataset(class_data(), batch_size=32, device_cache=False),
        module,
        grab,
    ]
    if drain_after is not None:
        capsules.append(DrainAt(drain_after))
    capsules.append(
        rt.Checkpointer(output_dir=ckpt_dir, save_every=save_every,
                        resume_from="latest", keep_last=keep_last)
    )
    launcher = rt.Launcher(
        [rt.Looper(capsules, tag="train", progress=False)],
        num_epochs=num_epochs, runtime=runtime,
    )
    return launcher, grab


def test_looper_drain_checkpoints_and_exits_drained(tmp_path):
    """The full worker-side drain path, in process: a drain request is
    honored at the next wave boundary — synchronous emergency checkpoint
    in the numbered layout (drain.json marker, capsules included), then
    GracefulDrain(EXIT_DRAINED) through the normal teardown — and a
    fresh run resumes from it via resume_from="latest" and completes."""
    ckpt_dir = str(tmp_path / "ckpts")
    runtime = Runtime(seed=0, project_dir=str(tmp_path), telemetry=True)
    launcher, grab = _tree(runtime, ckpt_dir, drain_after=3)
    with pytest.raises(SystemExit) as excinfo:
        launcher.launch()
    assert excinfo.value.code == EXIT_DRAINED
    assert isinstance(excinfo.value, GracefulDrain)

    # Drain happened at the boundary AFTER wave 3: the checkpoint is the
    # numbered step-3 directory, complete and marked as a drain save.
    path = excinfo.value.checkpoint
    assert path is not None and os.path.isdir(path), path
    assert os.path.basename(path) == "3"
    assert is_complete_checkpoint(path)
    assert newest_complete_step(ckpt_dir) == 3
    with open(os.path.join(path, "drain.json")) as f:
        marker = json.load(f)
    assert marker["reason"] == "drain" and marker["step"] == 3
    assert os.path.exists(os.path.join(path, "capsules.pkl"))
    # The drain rode the telemetry registry and teardown still flushed.
    tel = os.path.join(str(tmp_path), "runs", "telemetry", "telemetry.json")
    assert os.path.exists(tel)
    with open(tel) as f:
        assert json.load(f)["metrics"]["counters"]["resilience/drains"] == 1

    # Restart: resume_from="latest" picks the drain checkpoint; training
    # continues mid-epoch and completes both epochs (4 waves/epoch).
    runtime2 = Runtime(seed=0, project_dir=str(tmp_path / "r2"))
    launcher2, grab2 = _tree(runtime2, ckpt_dir)
    launcher2.launch()
    assert int(np.asarray(grab2.step)) == 8
    for leaf in jax.tree.leaves(jax.device_get(grab2.params)):
        assert np.isfinite(leaf).all()


def test_drain_checkpoint_joins_keep_last_rotation_after_resume(tmp_path):
    """The drain step must be recorded in the PICKLED capsule state —
    appended to saved_steps BEFORE save_emergency snapshots capsules (the
    _save_sync idiom) — so a resumed run's keep_last rotation prunes the
    drain directory like any periodic save. Without the ordering, every
    drain leaks a full checkpoint on disk forever."""
    ckpt_dir = str(tmp_path / "ckpts")
    runtime = Runtime(seed=0, project_dir=str(tmp_path))
    launcher, _ = _tree(runtime, ckpt_dir, drain_after=3)
    with pytest.raises(SystemExit):
        launcher.launch()
    assert os.path.isdir(os.path.join(ckpt_dir, "3"))

    # Resume with a rotating Checkpointer: saves at 4/6/8 with
    # keep_last=2 must rotate the step-3 drain save out.
    runtime2 = Runtime(seed=0, project_dir=str(tmp_path / "r2"))
    launcher2, _ = _tree(runtime2, ckpt_dir, save_every=2, keep_last=2)
    launcher2.launch()
    assert not os.path.exists(os.path.join(ckpt_dir, "3"))
    assert newest_complete_step(ckpt_dir) == 8


def test_drain_marker_written_over_complete_periodic_save(tmp_path):
    """A drain boundary can coincide with a step a periodic save already
    covered: the emergency rewrite is skipped, but the drain.json marker
    must still land — the smoke's marker assertion holds at ANY drain
    step, not just the 4-in-5 that miss a save boundary."""
    ckpt_dir = str(tmp_path / "ckpts")
    runtime = Runtime(seed=0, project_dir=str(tmp_path))
    launcher, _ = _tree(runtime, ckpt_dir, drain_after=3, save_every=3)
    with pytest.raises(SystemExit) as excinfo:
        launcher.launch()
    assert excinfo.value.code == EXIT_DRAINED
    path = excinfo.value.checkpoint
    assert os.path.basename(path) == "3"
    assert is_complete_checkpoint(path)
    with open(os.path.join(path, "drain.json")) as f:
        assert json.load(f)["step"] == 3


def test_drain_in_checkpointerless_phase_saves_via_registry(tmp_path):
    """A SIGTERM landing during a phase that owns no Checkpointer (the
    eval Looper) must still checkpoint: the runtime-wide registry reaches
    the train phase's Checkpointer — phase-subtree find() alone would
    come back empty and drop all progress since the last periodic save."""
    ckpt_dir = str(tmp_path / "ckpts")
    runtime = Runtime(seed=0, project_dir=str(tmp_path))
    module = rt.Module(
        MLP(in_features=8, num_classes=4, hidden=(16,)),
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    launcher = rt.Launcher(
        [
            rt.Looper(
                [rt.Dataset(class_data(), batch_size=32, device_cache=False),
                 module,
                 rt.Checkpointer(output_dir=ckpt_dir, save_every=1000)],
                tag="train", progress=False),
            rt.Looper(
                [rt.Dataset(class_data(), batch_size=32, device_cache=False),
                 rt.Module(MLP(in_features=8, num_classes=4, hidden=(16,))),
                 DrainAt(2)],
                tag="val", grad_enabled=False, progress=False),
        ],
        num_epochs=1, runtime=runtime,
    )
    with pytest.raises(SystemExit) as excinfo:
        launcher.launch()
    assert excinfo.value.code == EXIT_DRAINED
    path = excinfo.value.checkpoint
    assert path is not None and is_complete_checkpoint(path)
    assert os.path.exists(os.path.join(path, "drain.json"))


def test_looper_drain_without_checkpointer_still_exits(tmp_path):
    runtime = Runtime(seed=0, project_dir=str(tmp_path))
    module = rt.Module(
        MLP(in_features=8, num_classes=4, hidden=(16,)),
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    launcher = rt.Launcher(
        [rt.Looper(
            [rt.Dataset(class_data(), batch_size=32, device_cache=False),
             module, DrainAt(2)],
            tag="train", progress=False)],
        num_epochs=1, runtime=runtime,
    )
    with pytest.raises(SystemExit) as excinfo:
        launcher.launch()
    assert excinfo.value.code == EXIT_DRAINED
    assert excinfo.value.checkpoint is None


def test_fault_injected_kill_through_real_loop(tmp_path, monkeypatch):
    """A FaultPlan kill wired through env -> Runtime -> Looper.step_hook:
    the injector consults the REAL loop path. The kill action is swapped
    for a recorder (actually SIGKILLing pytest would be rude)."""
    monkeypatch.setenv("ROCKET_TPU_FAULTS", "kill:step=2")
    runtime = Runtime(seed=0, project_dir=str(tmp_path))
    assert runtime.faults is not None
    died = []
    runtime.faults._kill = lambda: (_ for _ in ()).throw(
        KeyboardInterrupt("injected-kill"))
    runtime.faults._note = lambda *a, **k: died.append(a)
    module = rt.Module(
        MLP(in_features=8, num_classes=4, hidden=(16,)),
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    launcher = rt.Launcher(
        [rt.Looper(
            [rt.Dataset(class_data(), batch_size=32, device_cache=False),
             module],
            tag="train", progress=False)],
        num_epochs=1, runtime=runtime,
    )
    with pytest.raises(KeyboardInterrupt):
        launcher.launch()
    assert len(died) == 1


# -- watchdog escalation -> restartable exit ---------------------------------


def test_escalation_exit_under_supervision(monkeypatch):
    from rocket_tpu.obs.telemetry import Telemetry

    exits = []
    monkeypatch.setattr(os, "_exit", exits.append)
    telemetry = Telemetry(enabled=True)
    telemetry.escalation_exit_code = EXIT_WEDGED
    telemetry._on_stall_escalation("wedged report")
    assert exits == [EXIT_WEDGED]
    # Without the supervisor wiring, escalation stays diagnostic-only.
    exits.clear()
    telemetry.escalation_exit_code = None
    telemetry._on_stall_escalation("wedged report")
    assert exits == []


def test_runtime_supervised_env_arms_escalation_exit(tmp_path, monkeypatch):
    monkeypatch.setenv("ROCKET_TPU_SUPERVISED", "1")
    previous = signal.getsignal(signal.SIGTERM)
    try:
        runtime = Runtime(seed=0, project_dir=str(tmp_path), telemetry=True)
        assert runtime.supervised
        assert runtime.telemetry.escalation_exit_code == EXIT_WEDGED
        # The SIGTERM->drain handler was installed by the Runtime.
        os.kill(os.getpid(), signal.SIGTERM)
        assert runtime.drain.requested
    finally:
        signal.signal(signal.SIGTERM, previous)


# -- supervisor control flow -------------------------------------------------


def _touch_checkpoint(ckpt_dir, step):
    path = os.path.join(ckpt_dir, str(step))
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "rng.json"), "w") as f:
        f.write("{}")


class ScriptedRunner:
    """Generation runner for supervisor unit tests: each entry is either
    an exit code or a callable(gen, nproc) -> rc run before returning."""

    def __init__(self, script, durations=None, clock=None):
        self.script = list(script)
        self.calls = []
        self.durations = durations or {}
        self.clock = clock

    def __call__(self, gen, nproc, drain_event, on_poll):
        self.calls.append((gen, nproc))
        entry = self.script.pop(0)
        rc = entry(gen, nproc) if callable(entry) else entry
        if self.clock is not None:
            self.clock.advance(self.durations.get(gen, 0.0))
        on_poll()
        return rc, [rc] * nproc, {"0": ["tail line"]}


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        # Each read advances a hair so probe throttling (>= 1s apart)
        # cannot starve the progress observation in tests.
        self.t += 1.01
        return self.t

    def advance(self, s):
        self.t += s


def _supervisor(tmp_path, script, nproc=1, policy=None, ckpt_dir=None,
                clock=None, durations=None):
    runner = ScriptedRunner(script, durations=durations, clock=clock)
    sup = Supervisor(
        nproc, "train.py",
        policy=policy or RestartPolicy(backoff_base_s=0.0, backoff_max_s=0.0,
                                       progress_grace_s=1e9),
        state_dir=str(tmp_path / "state"),
        ckpt_dir=ckpt_dir,
        run_generation=runner,
        sleep=lambda s: None,
        clock=clock or FakeClock(),
    )
    return sup, runner


def test_supervisor_restarts_until_completion(tmp_path):
    ckpt = str(tmp_path / "ckpts")
    os.makedirs(ckpt)

    def crash_with_progress(gen, nproc):
        _touch_checkpoint(ckpt, 5 * (gen + 1))
        return -9  # SIGKILLed worker

    sup, runner = _supervisor(
        tmp_path, [crash_with_progress, crash_with_progress, 0],
        ckpt_dir=ckpt,
    )
    rc = sup.run()
    assert rc == 0
    assert sup.outcome == "completed"
    assert sup.restarts == 2
    assert [g.outcome for g in sup.generations] == [
        "crashed", "crashed", "completed"]
    assert all(g.progressed for g in sup.generations[:2])
    state = json.load(open(os.path.join(str(tmp_path / "state"),
                                        "supervisor.json")))
    assert state["outcome"] == "completed" and state["restarts"] == 2
    assert state["last_ckpt_step"] == 10
    assert 0.0 <= state["goodput_fraction"] <= 1.0


def test_supervisor_crash_loop_refuses_to_thrash(tmp_path):
    policy = RestartPolicy(crash_loop_threshold=3, backoff_base_s=0.0,
                           progress_grace_s=1e9, max_restarts=100)
    sup, runner = _supervisor(tmp_path, [1, 1, 1, 1, 1], policy=policy)
    rc = sup.run()
    assert rc == 1
    assert sup.outcome == "crash_loop"
    # threshold consecutive no-progress failures -> exactly 3 generations.
    assert len(sup.generations) == 3
    # The failing generation's output tail is the supervisor's black box.
    assert sup.generations[-1].output_tail == {"0": ["tail line"]}
    state = json.load(open(os.path.join(str(tmp_path / "state"),
                                        "supervisor.json")))
    assert state["outcome"] == "crash_loop" and state["rc"] == 1


def test_supervisor_restart_budget(tmp_path):
    policy = RestartPolicy(max_restarts=2, crash_loop_threshold=100,
                           backoff_base_s=0.0, progress_grace_s=1e9)
    sup, runner = _supervisor(tmp_path, [7, 7, 7, 7], policy=policy)
    rc = sup.run()
    assert rc == 7
    assert sup.outcome == "restart_budget_exhausted"
    assert sup.restarts == 2 and len(sup.generations) == 3


def test_supervisor_honors_drained_exit(tmp_path):
    sup, runner = _supervisor(tmp_path, [EXIT_DRAINED])
    rc = sup.run()
    assert rc == 0
    assert sup.outcome == "drained"
    assert sup.generations[0].outcome == "drained"


def test_supervisor_drained_exit_requires_checkpoint_under_probe(tmp_path):
    """With a --ckpt-dir probe, rc 0 on a drain certifies a durable
    checkpoint to resume from: a worker exiting the drained code while
    the probe sees an EMPTY checkpoint dir (checkpointer-less script,
    every save torn) is drain_failed, not a clean stop an orchestrator
    would read as state-saved."""
    ckpt = str(tmp_path / "ckpts")
    os.makedirs(ckpt)
    sup, _ = _supervisor(tmp_path, [EXIT_DRAINED], ckpt_dir=ckpt)
    rc = sup.run()
    assert rc != 0 and sup.outcome == "drain_failed"

    # With a complete checkpoint on disk the same exit IS certified.
    _touch_checkpoint(ckpt, 7)
    sup2, _ = _supervisor(tmp_path, [EXIT_DRAINED], ckpt_dir=ckpt)
    rc2 = sup2.run()
    assert rc2 == 0 and sup2.outcome == "drained"


def test_supervisor_sigint_drains_then_restores_previous_handler(tmp_path):
    """First Ctrl-C requests the drain and restores the previous SIGINT
    disposition (so a second Ctrl-C interrupts hard — the worker-side
    install_signal_drain contract); SIGTERM stays routed to drain."""
    sup, _ = _supervisor(tmp_path, [0])
    prev_int = signal.getsignal(signal.SIGINT)
    prev_term = signal.getsignal(signal.SIGTERM)
    try:
        sup.install_signal_handlers()
        os.kill(os.getpid(), signal.SIGINT)
        assert sup.drain_signals == 1
        assert signal.getsignal(signal.SIGINT) is prev_int
        os.kill(os.getpid(), signal.SIGTERM)
        assert sup.drain_signals == 2
    finally:
        signal.signal(signal.SIGINT, prev_int)
        signal.signal(signal.SIGTERM, prev_term)


def test_supervisor_classifies_wedged(tmp_path):
    policy = RestartPolicy(crash_loop_threshold=2, backoff_base_s=0.0,
                           progress_grace_s=1e9)
    sup, runner = _supervisor(tmp_path, [EXIT_WEDGED, EXIT_WEDGED],
                              policy=policy)
    rc = sup.run()
    assert rc != 0
    assert [g.outcome for g in sup.generations] == ["wedged", "wedged"]


def test_supervisor_degrades_topology(tmp_path):
    """Repeated no-progress failures at one worker count re-resolve the
    topology: -n shrinks toward min_procs (the surviving mesh)."""
    policy = RestartPolicy(degrade_after=2, min_procs=1,
                           crash_loop_threshold=100, max_restarts=100,
                           backoff_base_s=0.0, progress_grace_s=1e9)
    sup, runner = _supervisor(tmp_path, [1, 1, 1, 1, 0], nproc=3,
                              policy=policy)
    rc = sup.run()
    assert rc == 0
    assert [c[1] for c in runner.calls] == [3, 3, 2, 2, 1]


def test_supervisor_degrades_to_floor_before_declaring_crash_loop(tmp_path):
    """With the DEFAULT thresholds (degrade_after=2 < crash_loop=3) a
    persistently-failing run must walk the topology all the way to
    min_procs before giving up: degrade is evaluated before the
    crash-loop verdict and resets the failure streak (re-resolution is
    the recovery action), so only the floor can declare a crash loop."""
    policy = RestartPolicy(degrade_after=2, crash_loop_threshold=3,
                           min_procs=1, max_restarts=100,
                           backoff_base_s=0.0, progress_grace_s=1e9)
    sup, runner = _supervisor(tmp_path, [1] * 7, nproc=3, policy=policy)
    rc = sup.run()
    assert rc == 1
    assert sup.outcome == "crash_loop"
    # 3,3 -> degrade; 2,2 -> degrade; 1,1,1 -> crash loop at the floor.
    assert [c[1] for c in runner.calls] == [3, 3, 2, 2, 1, 1, 1]


def test_supervisor_backoff_is_capped_exponential():
    policy = RestartPolicy(backoff_base_s=0.5, backoff_factor=2.0,
                           backoff_max_s=4.0)
    assert [policy.backoff_s(n) for n in range(1, 6)] == [
        0.5, 1.0, 2.0, 4.0, 4.0]


def test_supervisor_goodput_credits_salvaged_checkpoint_time(tmp_path):
    """A crashed generation is productive up to its last observed
    checkpoint advance; a completed generation is productive end-to-end."""
    ckpt = str(tmp_path / "ckpts")
    os.makedirs(ckpt)
    clock = FakeClock()

    def crash_after_ckpt(gen, nproc):
        _touch_checkpoint(ckpt, 5)
        return -9

    sup, runner = _supervisor(
        tmp_path, [crash_after_ckpt, 0], ckpt_dir=ckpt, clock=clock,
        durations={0: 10.0, 1: 20.0},
    )
    rc = sup.run()
    assert rc == 0
    gen0, gen1 = sup.generations
    assert gen0.productive_s > 0.0          # salvage credited
    assert gen0.productive_s <= gen0.duration_s
    assert gen1.productive_s == pytest.approx(gen1.duration_s)
    summary = sup.summary()
    assert 0.0 < summary["goodput_fraction"] <= 1.0


def test_supervisor_drain_event_stops_the_loop(tmp_path):
    """A drain signal that cannot be honored by an actual worker drain is
    never certified clean: arriving while workers crash -> drain_failed
    (non-zero), and arriving during the inter-generation backoff (the
    last generation CRASHED, no drain checkpoint exists) -> the same
    drain_failed verdict, not a rc-0 "drained" that an orchestrator
    would read as durably-saved state."""
    sup, runner = _supervisor(tmp_path, [1])
    sup.request_drain("SIGTERM")
    rc = sup.run()
    assert rc != 0 and sup.outcome == "drain_failed"

    sup2, runner2 = _supervisor(tmp_path, [1, 0])
    sup2._sleep = lambda s: sup2._drain_event.set()  # SIGTERM mid-backoff
    rc2 = sup2.run()
    assert rc2 != 0 and sup2.outcome == "drain_failed"
    # The scripted second generation never ran — the drain stopped the loop.
    assert len(sup2.generations) == 1


def test_supervisor_coord_error_not_counted_as_crash_loop(tmp_path):
    """Fast coordinator bind/connect failures (the runner's optional
    fourth return element, fed by WorkerGroup.coord_error) are
    infrastructure noise: they must not feed the degrade/crash-loop
    counters — only the restart budget bounds them."""

    class CoordErrorRunner(ScriptedRunner):
        def __call__(self, gen, nproc, drain_event, on_poll):
            rc, codes, tail = super().__call__(gen, nproc, drain_event,
                                               on_poll)
            return rc, codes, tail, rc != 0

    runner = CoordErrorRunner([1, 1, 1, 1, 0])
    policy = RestartPolicy(backoff_base_s=0.0, backoff_max_s=0.0,
                           progress_grace_s=1e9, crash_loop_threshold=3,
                           degrade_after=2, min_procs=1)
    sup = Supervisor(
        2, "train.py", policy=policy, state_dir=str(tmp_path / "state"),
        run_generation=runner, sleep=lambda s: None, clock=FakeClock(),
    )
    rc = sup.run()
    # Four coordinator failures would have tripped degrade_after=2 (to
    # nproc=1) and crash_loop_threshold=3; instead every generation ran
    # at the full count and the run completed.
    assert rc == 0 and sup.outcome == "completed"
    assert [n for _, n in runner.calls] == [2, 2, 2, 2, 2]
    assert all(g.coord_error for g in sup.generations[:4])
    assert not sup.generations[-1].coord_error


def test_supervisor_ckpt_probe_overrides_duration_heuristic(tmp_path):
    """With a --ckpt-dir probe, durable checkpoint advance is the ONLY
    progress evidence: a deterministic crasher whose startup outlives
    progress_grace_s must still trip the crash-loop detector instead of
    thrashing through the whole restart budget."""
    ckpt = str(tmp_path / "ckpts")
    os.makedirs(ckpt)
    clock = FakeClock()
    policy = RestartPolicy(backoff_base_s=0.0, backoff_max_s=0.0,
                           progress_grace_s=5.0, crash_loop_threshold=3,
                           max_restarts=50)
    sup, runner = _supervisor(
        tmp_path, [1, 1, 1, 1], policy=policy, ckpt_dir=ckpt, clock=clock,
        durations={0: 60.0, 1: 60.0, 2: 60.0},  # each gen outlives the grace
    )
    rc = sup.run()
    assert rc != 0 and sup.outcome == "crash_loop"
    assert len(sup.generations) == 3
    assert not any(g.progressed for g in sup.generations)


# -- checkpoint-completeness scan -------------------------------------------


def test_complete_checkpoint_scan(tmp_path):
    root = str(tmp_path)
    assert newest_complete_step(root) is None
    assert newest_complete_step(None) is None
    _touch_checkpoint(root, 4)
    _touch_checkpoint(root, 9)
    assert newest_complete_step(root) == 9
    # A model dir whose index references a missing shard file is torn.
    torn = os.path.join(root, "12", "model_0")
    os.makedirs(torn)
    with open(os.path.join(root, "12", "rng.json"), "w") as f:
        f.write("{}")
    with open(os.path.join(torn, "index.json"), "w") as f:
        json.dump({"params/w": {"kind": "array", "chunks": [
            {"file": "shard_p0.npz", "key": "k", "index": [[0, 1]]}]}}, f)
    assert not is_complete_checkpoint(os.path.join(root, "12"))
    assert newest_complete_step(root) == 9


def test_obs_report_renders_supervisor_json(tmp_path, capsys):
    from rocket_tpu.obs.__main__ import main as obs_main

    doc = {
        "outcome": "completed", "restarts": 1, "drain_events": 0,
        "goodput_fraction": 0.83, "productive_wall_s": 10.0,
        "total_wall_s": 12.0,
        "generations": [
            {"gen": 0, "nproc": 1, "outcome": "crashed", "duration_s": 2.0,
             "productive_s": 0.5, "rc": -9, "ckpt_step": 5},
            {"gen": 1, "nproc": 1, "outcome": "completed", "duration_s": 10.0,
             "productive_s": 10.0, "rc": 0, "ckpt_step": 40},
        ],
    }
    path = tmp_path / "supervisor.json"
    path.write_text(json.dumps(doc))
    assert obs_main(["report", str(path)]) == 0
    out = capsys.readouterr().out
    assert "supervisor: outcome=completed" in out
    assert "goodput_fraction=0.83" in out
    assert "crashed" in out and "completed" in out
