import numpy as np
import pytest

from rocket_tpu.data.collate import default_collate, default_move
from rocket_tpu.data.loader import DataLoader


# -- collate semantics (utils.py:16-27, verified in SURVEY §2a) --------------


def test_arrays_stack():
    out = default_collate([np.zeros((2, 3)), np.ones((2, 3))])
    assert out.shape == (2, 2, 3)


def test_strings_pass_through():
    assert default_collate(["a", "b"]) == ["a", "b"]


def test_scalars_pass_through():
    assert default_collate([1, 2, 3]) == [1, 2, 3]
    assert default_collate([1.5, 2.5]) == [1.5, 2.5]


def test_tuples_pass_through_uncollated():
    # Verified reference quirk: tuple samples yield an uncollated list of tuples.
    samples = [(np.zeros(2), 0), (np.ones(2), 1)]
    out = default_collate(samples)
    assert isinstance(out, list)
    assert isinstance(out[0], tuple)


def test_dicts_collate_per_key():
    out = default_collate([{"x": np.zeros(2), "y": 1}, {"x": np.ones(2), "y": 2}])
    assert out["x"].shape == (2, 2)
    assert out["y"] == [1, 2]


def test_lists_collate_per_element():
    out = default_collate([[np.zeros(2), "a"], [np.ones(2), "b"]])
    assert isinstance(out, list)
    assert out[0].shape == (2, 2)
    assert out[1] == ["a", "b"]


def test_move_preserves_structure(runtime):
    import jax

    tree = {"x": np.zeros((2, 2)), "s": "keep", "n": 5, "t": (np.ones(2), "y")}
    moved = default_move(tree, runtime.device)
    assert isinstance(moved["x"], jax.Array)
    assert moved["s"] == "keep"
    assert moved["n"] == 5
    assert isinstance(moved["t"][0], jax.Array)
    assert moved["t"][1] == "y"


# -- DataLoader --------------------------------------------------------------


def samples(n):
    return [{"x": np.full((4,), i, np.float32), "i": np.int32(i)} for i in range(n)]


def test_batching_and_len():
    dl = DataLoader(samples(10), batch_size=4)
    assert len(dl) == 3  # ceil
    batches = list(dl)
    assert batches[0].data["x"].shape == (4, 4)
    assert batches[0].size == 4


def test_drop_last():
    dl = DataLoader(samples(10), batch_size=4, drop_last=True)
    assert len(dl) == 2
    assert all(b.size == 4 for b in dl)


def test_last_batch_wrap_padding_records_real_size():
    dl = DataLoader(samples(10), batch_size=4)
    last = list(dl)[-1]
    assert last.data["x"].shape == (4, 4)  # padded to full batch
    assert last.size == 2  # but only 2 real samples


def test_shuffle_deterministic_per_epoch():
    dl = DataLoader(samples(16), batch_size=4, shuffle=True, seed=7)
    dl.set_epoch(0)
    first = [b.data["i"].tolist() for b in dl]
    dl.set_epoch(0)
    again = [b.data["i"].tolist() for b in dl]
    dl.set_epoch(1)
    other = [b.data["i"].tolist() for b in dl]
    assert first == again
    assert first != other
    # still a permutation of everything
    assert sorted(sum(other, [])) == list(range(16))


def test_no_shuffle_is_sequential():
    dl = DataLoader(samples(8), batch_size=4)
    order = [b.data["i"].tolist() for b in dl]
    assert order == [[0, 1, 2, 3], [4, 5, 6, 7]]


def test_skip_fast_forwards():
    dl = DataLoader(samples(12), batch_size=4)
    dl.skip(2)
    batches = list(dl)
    assert len(batches) == 1
    assert batches[0].index == 2
    assert batches[0].data["i"].tolist() == [8, 9, 10, 11]
    # skip consumed — next epoch is full again
    assert len(list(dl)) == 3


def test_host_striping_partitions_batch():
    # Two "hosts" must see disjoint halves of each global batch.
    a = DataLoader(samples(8), batch_size=4, process_index=0, process_count=2)
    b = DataLoader(samples(8), batch_size=4, process_index=1, process_count=2)
    batch_a = next(iter(a))
    batch_b = next(iter(b))
    assert batch_a.data["i"].tolist() == [0, 1]
    assert batch_b.data["i"].tolist() == [2, 3]


def test_global_batch_must_divide_hosts():
    with pytest.raises(ValueError, match="divide"):
        DataLoader(samples(8), batch_size=3, process_count=2)


def test_iterable_dataset():
    def gen():
        for i in range(8):
            yield {"x": np.full((2,), i, np.float32)}

    class Iterable:
        def __iter__(self):
            return gen()

    dl = DataLoader(Iterable(), batch_size=4)
    assert dl.total is None
    batches = list(dl)
    assert len(batches) == 2
    assert batches[0].data["x"].shape == (4, 2)


def test_wrap_padding_tiles_tiny_dataset():
    """len(dataset) < pad length must still produce a full global batch
    (short stripes would hang multihost collectives) — ADVICE r1."""
    ds = [{"x": np.float32(i)} for i in range(3)]
    batches = list(DataLoader(ds, batch_size=8))
    assert len(batches) == 1
    assert batches[0].size == 3
    np.testing.assert_array_equal(
        batches[0].data["x"], np.array([0, 1, 2, 0, 1, 2, 0, 1], np.float32)
    )


def test_prefetch_iterator_matches_direct_iteration():
    from rocket_tpu.data.prefetch import PrefetchIterator

    ds = [{"x": np.float32(i)} for i in range(37)]
    direct = [b.data["x"] for b in DataLoader(ds, batch_size=4)]
    pre = [
        b.data["x"]
        for b in PrefetchIterator(iter(DataLoader(ds, batch_size=4)), depth=3)
    ]
    assert len(direct) == len(pre)
    for d, p in zip(direct, pre):
        np.testing.assert_array_equal(d, p)


def test_prefetch_iterator_propagates_errors_and_closes():
    from rocket_tpu.data.prefetch import PrefetchIterator

    def boom():
        yield 1
        raise ValueError("worker died")

    it = PrefetchIterator(boom(), depth=2)
    assert next(it) == 1
    with pytest.raises(ValueError, match="worker died"):
        next(it)

    # Early close doesn't hang even with a blocked producer.
    slow = PrefetchIterator(iter(range(1000)), depth=1)
    assert next(slow) == 0
    slow.close()
    with pytest.raises(StopIteration):
        next(slow)


def test_prefetch_transform_runs_on_worker():
    from rocket_tpu.data.prefetch import PrefetchIterator

    out = list(PrefetchIterator(iter([1, 2, 3]), transform=lambda x: x * 10))
    assert out == [10, 20, 30]


def test_num_workers_matches_serial_order_and_content():
    """Multiprocess loading yields byte-identical batches in the same order
    as the serial path (same shuffle permutation, same wrap padding)."""
    import numpy as np

    from rocket_tpu.data.datasets import ArrayDataset
    from rocket_tpu.data.loader import DataLoader

    rng = np.random.default_rng(0)
    data = ArrayDataset(
        rng.normal(size=(70, 5)).astype(np.float32),
        rng.integers(0, 3, size=70).astype(np.int32),
    )
    serial = DataLoader(data, batch_size=16, shuffle=True, seed=3)
    workers = DataLoader(data, batch_size=16, shuffle=True, seed=3,
                         num_workers=2)
    try:
        for epoch in (0, 1):
            serial.set_epoch(epoch)
            workers.set_epoch(epoch)
            got = list(workers)
            want = list(serial)
            assert [b.index for b in got] == [b.index for b in want]
            assert [b.size for b in got] == [b.size for b in want]
            for a, b in zip(got, want):
                for ka, kb in zip(
                    sorted(a.data), sorted(b.data)
                ):
                    np.testing.assert_array_equal(a.data[ka], b.data[kb])
    finally:
        workers.close()


class PerSample:
    """Module-level so it pickles into spawn/forkserver workers."""

    def __len__(self):
        return 10

    def __getitem__(self, i):
        import numpy as np

        return {"x": np.full((3,), i, np.float32)}


def test_num_workers_per_sample_dataset_and_errors():
    import numpy as np
    import pytest

    from rocket_tpu.data.loader import DataLoader

    loader = DataLoader(PerSample(), batch_size=4, num_workers=2)
    try:
        batches = list(loader)
        assert len(batches) == 3
        np.testing.assert_array_equal(
            batches[0].data["x"][:, 0], np.array([0, 1, 2, 3], np.float32)
        )
        assert batches[-1].size == 2  # wrap-padded trailing batch
    finally:
        loader.close()

    with pytest.raises(ValueError, match="map-style"):
        DataLoader(iter(range(5)), batch_size=2, num_workers=2)


def test_default_worker_start_method_avoids_fork_warning():
    """The default start method must not os.fork() the (multithreaded) JAX
    parent: JAX's 'os.fork() is incompatible with multithreaded code'
    RuntimeWarning stays silent, and 'fork' remains an explicit opt-in."""
    import warnings

    import jax
    import numpy as np

    from rocket_tpu.data.datasets import ArrayDataset
    from rocket_tpu.data.loader import DataLoader
    from rocket_tpu.data.workers import default_start_method

    jax.devices()  # ensure the backend (and its threads) are up

    assert default_start_method() in ("forkserver", "spawn")

    data = ArrayDataset(
        np.arange(64, dtype=np.float32).reshape(16, 4),
        np.zeros(16, np.int32),
    )
    loader = DataLoader(data, batch_size=4, num_workers=2)
    with warnings.catch_warnings(record=True) as record:
        warnings.simplefilter("always")
        try:
            batches = list(loader)
            pool = loader._worker_pool
        finally:
            loader.close()
    assert len(batches) == 4
    assert pool.start_method == default_start_method()
    fork_warnings = [
        w for w in record if "os.fork" in str(w.message)
    ]
    assert not fork_warnings, [str(w.message) for w in fork_warnings]


def test_device_cache_dtype_and_store_keying():
    """cache_dtype stores float leaves at compute precision; two Datasets
    over the same raw data with different cache dtypes must not share one
    cache entry."""
    import jax
    import jax.numpy as jnp

    from rocket_tpu.core.dataset import Dataset
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(seed=0)
    raw = [
        {"x": np.full((4,), float(i), np.float32), "y": np.int32(i)}
        for i in range(8)
    ]
    d_bf16 = Dataset(raw, batch_size=4, cache_dtype=jnp.bfloat16,
                     statefull=False, runtime=runtime)
    d_f32 = Dataset(raw, batch_size=4, statefull=False, runtime=runtime)
    d_bf16.setup()
    d_f32.setup()
    cache_bf16 = d_bf16._dataloader.cache
    cache_f32 = d_f32._dataloader.cache
    assert cache_bf16["x"].dtype == jnp.bfloat16
    assert cache_bf16["y"].dtype == jnp.int32  # ints untouched
    assert cache_f32["x"].dtype == jnp.float32
    assert len(runtime.device_cache_store) == 2  # separate entries


def test_slice_marker_on_unshuffled_contiguous_batches():
    """Unshuffled device-cached batches are contiguous cache runs, so the
    fused marker degrades to "_device_slice" (dynamic_slice instead of a
    general gather — round-4 verdict ask #2). Shuffled or wrap-padded
    epochs must keep the gather marker; both materialize identical rows."""
    import jax.numpy as jnp

    from rocket_tpu.data.device_cache import (
        DeviceCachedLoader, materialize_marker,
    )
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(seed=0)
    data = {
        "x": np.arange(24, dtype=np.float32).reshape(12, 2),
        "y": np.arange(12, dtype=np.int32),
    }

    seq = DeviceCachedLoader(data, batch_size=4, runtime=runtime)
    batches = list(seq)
    assert all("_device_slice" in b.data for b in batches)
    rows = [np.asarray(materialize_marker(b.data)["y"]) for b in batches]
    np.testing.assert_array_equal(np.concatenate(rows), data["y"])

    # Row shuffle -> gather marker (rows within a batch non-contiguous).
    shuf = DeviceCachedLoader(data, batch_size=4, runtime=runtime,
                              shuffle=True)
    assert all("_device_gather" in b.data for b in shuf)

    # Wrap-padded last batch (12 % 5 != 0, drop_last=False) -> gather.
    wrap = DeviceCachedLoader(data, batch_size=5, runtime=runtime)
    assert all("_device_gather" in b.data for b in wrap)

    # drop_last trims the remainder, so contiguity holds -> slice.
    trim = DeviceCachedLoader(data, batch_size=5, runtime=runtime,
                              drop_last=True)
    tb = list(trim)
    assert all("_device_slice" in b.data for b in tb)
    rows = [np.asarray(materialize_marker(b.data)["y"]) for b in tb]
    np.testing.assert_array_equal(np.concatenate(rows), data["y"][:10])
