"""rocket_tpu.obs.export + obs.slo — the live telemetry plane:
streaming JSONL shards, Prometheus text exposition, the /metrics
endpoint, cross-rank merge math, SLO burn-rate gates, and the obs
CLI's live subcommands (top / watch / report shard-fallback).

Deliberately jax-free: the whole plane is stdlib + registry dicts,
and these tests pin that (the supervisor imports it signal-safe)."""

import json
import math
import os
import urllib.error
import urllib.request

import pytest

from rocket_tpu.obs.export import (
    ExportConfig,
    PrometheusServer,
    ShardWriter,
    TelemetryExporter,
    host_identity,
    merge_rank_records,
    prometheus_name,
    read_shard_file,
    read_telemetry_dir,
    render_prometheus,
)
from rocket_tpu.obs.registry import MetricsRegistry, estimate_quantiles
from rocket_tpu.obs.slo import SLOEvaluator, SLOSpec, load_slo_specs
from rocket_tpu.obs.telemetry import Telemetry


def parse_prometheus(text: str) -> dict:
    """A deliberately tiny text-exposition (0.0.4) parser: enough of the
    grammar to verify what a real scraper would ingest. Returns
    ``{metric: {"type": kind, "samples": [(labels_dict, value)]}}`` where
    samples are keyed by the FULL sample name (incl. _bucket/_sum/_count)."""
    families: dict = {}
    samples: dict = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _, _, name, kind = line.split()
            families[name] = kind
        elif line and not line.startswith("#"):
            name_labels, raw = line.rsplit(" ", 1)
            labels = {}
            if "{" in name_labels:
                name, inner = name_labels.split("{", 1)
                for pair in inner.rstrip("}").split(","):
                    key, val = pair.split("=", 1)
                    labels[key] = val.strip('"')
            else:
                name = name_labels
            value = float(raw)
            samples.setdefault(name, []).append((labels, value))
    return {"types": families, "samples": samples}


# -- streaming shards ------------------------------------------------------


def test_shard_round_trip_skips_torn_last_line(tmp_path):
    """One complete JSON object per line; a crash mid-append tears at
    most the final line, which every reader skips — the shard's
    crash-readability contract."""
    path = str(tmp_path / "telemetry" / "rank0.jsonl")
    writer = ShardWriter(path)
    for seq in range(3):
        writer.append({"version": 1, "seq": seq, "rank": 0,
                       "metrics": {"gauges": {"perf/steps_per_sec": 40 + seq}}})
    # Simulate the crash: a torn, undecodable trailing line.
    with open(path, "a", encoding="utf-8") as f:
        f.write('{"version": 1, "seq": 3, "metr')
    records = read_shard_file(path)
    assert [r["seq"] for r in records] == [0, 1, 2]
    assert records[-1]["metrics"]["gauges"]["perf/steps_per_sec"] == 42
    # A fresh writer resumes the line count instead of clobbering.
    resumed = ShardWriter(path)
    resumed.append({"version": 1, "seq": 4})
    assert [r["seq"] for r in read_shard_file(path)] == [0, 1, 2, 4]


def test_shard_compaction_bounds_and_keeps_newest(tmp_path):
    path = str(tmp_path / "rank0.jsonl")
    writer = ShardWriter(path, retention_lines=10)
    for seq in range(25):
        writer.append({"seq": seq})
    records = read_shard_file(path)
    assert len(records) <= 10
    # Newest records survive compaction; no temp file left behind.
    assert records[-1]["seq"] == 24
    assert not os.path.exists(path + ".tmp")


def test_read_telemetry_dir_groups_by_rank(tmp_path):
    run = tmp_path / "run"
    for rank in (0, 2):
        ShardWriter(str(run / "telemetry" / f"rank{rank}.jsonl")).append(
            {"seq": 0, "rank": rank}
        )
    # Non-shard files are ignored.
    (run / "telemetry" / "notes.txt").write_text("hi")
    shards = read_telemetry_dir(str(run))
    assert sorted(shards) == [0, 2]
    # Resolving the telemetry dir itself works too.
    assert sorted(read_telemetry_dir(str(run / "telemetry"))) == [0, 2]
    assert read_telemetry_dir(str(tmp_path / "empty")) == {}


# -- Prometheus exposition -------------------------------------------------


def test_prometheus_name_mangling():
    assert prometheus_name("serve/ttft_s") == "rocket_tpu_serve_ttft_s"
    assert prometheus_name("obs/slo/x-y.z/burn_rate") == \
        "rocket_tpu_obs_slo_x_y_z_burn_rate"


def test_render_prometheus_buckets_cumulative_and_inf_closes():
    """The registry stores per-bucket counts; the exposition must be
    cumulative, closed by a mandatory +Inf bucket equal to _count."""
    registry = MetricsRegistry()
    registry.counter("serve/requests").inc(7)
    registry.gauge("goodput/goodput_fraction").set(0.85)
    hist = registry.histogram("serve/itl_s", base=1e-6)
    for value in (1e-6, 3e-6, 3e-6, 100e-6, 0.1):
        hist.observe(value)
    parsed = parse_prometheus(
        render_prometheus(registry.snapshot(), labels={"rank": 1})
    )
    assert parsed["types"]["rocket_tpu_serve_requests"] == "counter"
    assert parsed["types"]["rocket_tpu_goodput_goodput_fraction"] == "gauge"
    assert parsed["types"]["rocket_tpu_serve_itl_s"] == "histogram"
    (labels, value), = parsed["samples"]["rocket_tpu_serve_requests"]
    assert labels == {"rank": "1"} and value == 7.0
    buckets = parsed["samples"]["rocket_tpu_serve_itl_s_bucket"]
    # Cumulative: monotone non-decreasing in le order, +Inf last == count.
    ordered = sorted(buckets, key=lambda s: float(
        s[0]["le"].replace("+Inf", "inf")))
    counts = [value for _, value in ordered]
    assert counts == sorted(counts)
    assert ordered[-1][0]["le"] == "+Inf" and ordered[-1][1] == 5.0
    (_, count), = parsed["samples"]["rocket_tpu_serve_itl_s_count"]
    assert count == 5.0
    (_, total), = parsed["samples"]["rocket_tpu_serve_itl_s_sum"]
    assert total == pytest.approx(1e-6 + 3e-6 + 3e-6 + 100e-6 + 0.1)


def test_metrics_endpoint_serves_live_snapshots(tmp_path):
    """port=0 binds ephemeral; every scrape re-reads the registry (the
    second GET sees the gauge move); non-/metrics paths 404."""
    registry = MetricsRegistry()
    registry.gauge("train/step").set(1)
    server = PrometheusServer(registry.snapshot, port=0,
                              labels={"rank": 0})
    server.start()
    try:
        url = f"http://127.0.0.1:{server.port}/metrics"
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        assert 'rocket_tpu_train_step{rank="0"} 1' in body
        registry.gauge("train/step").set(2)
        body = urllib.request.urlopen(url, timeout=5).read().decode()
        assert 'rocket_tpu_train_step{rank="0"} 2' in body
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/nope", timeout=5
            )
    finally:
        server.stop()


# -- configuration ---------------------------------------------------------


def test_export_config_from_env(monkeypatch):
    monkeypatch.delenv("ROCKET_TPU_EXPORT", raising=False)
    monkeypatch.delenv("ROCKET_TPU_METRICS_PORT", raising=False)
    monkeypatch.delenv("ROCKET_TPU_SLO", raising=False)
    assert not ExportConfig.from_env().active
    # Numeric ROCKET_TPU_EXPORT enables AND sets the tick interval.
    monkeypatch.setenv("ROCKET_TPU_EXPORT", "2.5")
    config = ExportConfig.from_env()
    assert config.enabled and config.interval_s == 2.5
    # A bare truthy flag keeps the default cadence.
    monkeypatch.setenv("ROCKET_TPU_EXPORT", "1")
    assert ExportConfig.from_env().interval_s == 10.0
    monkeypatch.setenv("ROCKET_TPU_METRICS_PORT", "9099")
    monkeypatch.setenv("ROCKET_TPU_SLO", "default:train")
    config = ExportConfig.from_env()
    assert config.metrics_port == 9099 and config.slo_path == "default:train"
    # Explicit arguments win over the environment.
    config = ExportConfig.from_env(enabled=False, metrics_port=7)
    assert not config.enabled and config.metrics_port == 7 and config.active


def test_host_identity_reads_launcher_env(monkeypatch):
    monkeypatch.setenv("JAX_PROCESS_ID", "3")
    identity = host_identity()
    assert identity["rank"] == 3
    assert identity["hostname"] and identity["pid"] == os.getpid()
    assert host_identity(process_index=5)["rank"] == 5


# -- SLO burn rates --------------------------------------------------------


def test_slo_gauge_min_burn_and_warmup_grace():
    """goodput_fraction 0.0 at t=0 is a cold start, not an incident:
    warmup_s suppresses the violation while still reporting the burn.
    Past warmup the same burn violates, once (newly_violated edge)."""
    spec = SLOSpec(name="train_goodput", kind="gauge_min",
                   metric="goodput/goodput_fraction", objective=0.8,
                   warmup_s=30.0)
    evaluator = SLOEvaluator([spec])
    status, = evaluator.observe(
        0.0, {"gauges": {}}, {"goodput_fraction": 0.0})
    assert status.burn_rate == math.inf and not status.violated
    status, = evaluator.observe(
        10.0, {"gauges": {}}, {"goodput_fraction": 0.4})
    assert status.burn_rate == pytest.approx(2.0)
    assert not status.violated  # still inside warmup
    status, = evaluator.observe(
        60.0, {"gauges": {}}, {"goodput_fraction": 0.4})
    assert status.violated and status.newly_violated
    status, = evaluator.observe(
        70.0, {"gauges": {}}, {"goodput_fraction": 0.4})
    assert status.violated and not status.newly_violated
    # Recovery clears the latch; the next violation is "new" again.
    status, = evaluator.observe(
        80.0, {"gauges": {}}, {"goodput_fraction": 0.95})
    assert not status.violated and status.burn_rate < 1.0


def test_slo_gauge_max_burn():
    spec = SLOSpec(name="queue", kind="gauge_max",
                   metric="serve/queue_depth", objective=64.0)
    evaluator = SLOEvaluator([spec])
    status, = evaluator.observe(0.0, {"gauges": {"serve/queue_depth": 16.0}})
    assert status.burn_rate == pytest.approx(0.25) and not status.violated
    status, = evaluator.observe(1.0, {"gauges": {"serve/queue_depth": 128.0}})
    assert status.burn_rate == pytest.approx(2.0) and status.newly_violated
    # No data at all: burn 0, value None, no violation.
    status, = evaluator.observe(2.0, {"gauges": {}})
    assert status.value is None and status.burn_rate == 0.0


def test_slo_quantile_burn_true_positive_and_negative():
    """Quantile burn = bad_fraction / (1 - q) over windowed bucket
    deltas: a tail above the ceiling violates, an all-fast window does
    not, and the windowing ages the cold-start tail out."""
    spec = SLOSpec(name="itl_p99", kind="quantile", metric="serve/itl_s",
                   objective=1e-3, quantile=0.9, window_s=100.0)
    registry = MetricsRegistry()
    hist = registry.histogram("serve/itl_s", base=1e-6)
    evaluator = SLOEvaluator([spec])
    # Negative: 50 observations all well under the 1ms ceiling.
    for _ in range(50):
        hist.observe(1e-4)
    status, = evaluator.observe(0.0, registry.snapshot())
    assert not status.violated and status.burn_rate == 0.0
    assert status.value == pytest.approx(1e-4, rel=1.0)
    # True positive: half the next window sits 100x over the ceiling.
    for _ in range(50):
        hist.observe(1e-1)
    status, = evaluator.observe(10.0, registry.snapshot())
    assert status.violated
    assert status.burn_rate >= 1.0  # bad fraction ~0.5 vs budget 0.1
    # Window slide: a quiet period after the spike evaluates only the
    # (empty) delta — no data, no violation, the tail aged out.
    status, = evaluator.observe(200.0, registry.snapshot())
    assert status.value is None and not status.violated


def test_load_slo_specs_defaults_and_validation(tmp_path):
    serve = load_slo_specs("default:serve")
    train = load_slo_specs("default:train")
    assert {s.name for s in serve} >= {"serve_itl_p99", "serve_ttft_p99"}
    assert {s.name for s in train} >= {"train_goodput",
                                       "train_steps_per_sec"}
    # The budget-derived objectives resolved to real finite ceilings.
    for spec in serve:
        assert math.isfinite(spec.objective) and spec.objective > 0
    # Train specs carry the cold-start grace.
    assert all(s.warmup_s > 0 for s in train)
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps({"version": 1, "slos": [
        {"name": "x", "kind": "nope", "metric": "m", "objective": 1}
    ]}))
    with pytest.raises(ValueError):
        load_slo_specs(str(bad))
    with pytest.raises(ValueError):
        load_slo_specs("default:imaginary")


# -- cross-rank merge ------------------------------------------------------


def _rank_record(rank: int, steps_per_sec: float, requests: float,
                 itl_buckets: dict) -> dict:
    return {
        "rank": rank, "seq": 5, "t_unix": 1000.0, "uptime_s": 50.0,
        "hostname": f"host{rank}", "pid": 100 + rank,
        "goodput": {"goodput_fraction": 0.9},
        "metrics": {
            "counters": {"serve/requests": requests},
            "gauges": {"perf/steps_per_sec": steps_per_sec},
            "histograms": {"serve/itl_s": {
                "count": sum(itl_buckets.values()),
                "total": 1.0, "min": 1e-5, "max": 1e-2,
                "buckets": itl_buckets,
            }},
        },
    }


def test_merge_rank_records_math():
    latest = {
        0: _rank_record(0, 50.0, 100.0, {"le_1e-05": 10, "le_2e-05": 30}),
        1: _rank_record(1, 40.0, 120.0, {"le_2e-05": 10, "le_4e-05": 50}),
        2: _rank_record(2, 10.0, 80.0, {"le_1e-05": 5}),
    }
    merged = merge_rank_records(latest)
    assert merged["ranks"] == [0, 1, 2]
    # Counters: fleet total is the per-process sum.
    assert merged["counters"]["serve/requests"] == pytest.approx(300.0)
    # Gauges: spread stats with arg-min/arg-max rank attribution.
    stat = merged["gauges"]["perf/steps_per_sec"]
    assert stat["mean"] == pytest.approx(100.0 / 3)
    assert stat["min"] == 10.0 and stat["min_rank"] == 2
    assert stat["max"] == 50.0 and stat["max_rank"] == 0
    assert stat["skew"] == pytest.approx((50.0 - 10.0) / (100.0 / 3))
    # Histograms: buckets summed, quantile estimation works on the merge.
    hist = merged["histograms"]["serve/itl_s"]
    assert hist["count"] == 105
    assert hist["buckets"] == {"le_1e-05": 15, "le_2e-05": 40,
                               "le_4e-05": 50}
    assert hist["min"] == 1e-5 and hist["max"] == 1e-2
    quantiles = estimate_quantiles(hist)
    assert 1e-5 <= quantiles["p50"] <= 4e-5


def test_merge_uniform_fleet_has_zero_skew():
    latest = {r: _rank_record(r, 42.0, 1.0, {"le_1e-05": 1})
              for r in range(4)}
    stat = merge_rank_records(latest)["gauges"]["perf/steps_per_sec"]
    assert stat["skew"] == 0.0 and stat["mean"] == 42.0


# -- the exporter ----------------------------------------------------------


def test_exporter_tick_shard_schema_and_slo_gauges(tmp_path):
    """One synchronous tick: the shard record carries schema version,
    identity, goodput and the registry snapshot; a violated SLO becomes
    obs/slo/* gauges + a violation counter inside the same record."""
    spec_file = tmp_path / "slo.json"
    spec_file.write_text(json.dumps({"version": 1, "slos": [
        {"name": "steps_floor", "kind": "gauge_min",
         "metric": "perf/steps_per_sec", "objective": 100.0},
    ]}))
    telemetry = Telemetry(enabled=True, out_dir=str(tmp_path / "run"))
    telemetry.registry.gauge("perf/steps_per_sec").set(5.0)
    exporter = TelemetryExporter(
        telemetry,
        ExportConfig(enabled=True, slo_path=str(spec_file)),
        identity={"rank": 0, "hostname": "testhost", "pid": 1234},
    )
    record = exporter.tick()
    assert record["version"] == 1 and record["seq"] == 0
    assert record["rank"] == 0 and record["hostname"] == "testhost"
    assert not record["final"]
    assert record["goodput"]["goodput_fraction"] is not None
    # The SLO verdict rides the record AND the registry.
    verdict, = [s for s in record["slo"] if s["name"] == "steps_floor"]
    assert verdict["violated"] and verdict["burn_rate"] == pytest.approx(20.0)
    gauges = record["metrics"]["gauges"]
    assert gauges["obs/slo/steps_floor/violated"] == 1.0
    assert record["metrics"]["counters"][
        "obs/slo/steps_floor/violations"] == 1
    # On disk: one parseable line under <out_dir>/telemetry/rank0.jsonl.
    shard = tmp_path / "run" / "telemetry" / "rank0.jsonl"
    assert read_shard_file(str(shard))[0]["seq"] == 0
    final = exporter.tick(final=True)
    assert final["final"] and final["seq"] == 1
    # Sustained violation: the edge counter did not move again.
    assert final["metrics"]["counters"][
        "obs/slo/steps_floor/violations"] == 1


def test_exporter_migrates_shard_when_out_dir_resolves_late(tmp_path):
    """A Tracker suggesting runs/<project> after the first ticks must
    not split the shard history — the exporter carries the early file
    to the new path (os.replace) and appends there."""
    telemetry = Telemetry(enabled=True)
    exporter = TelemetryExporter(
        telemetry, ExportConfig(enabled=True),
        identity={"rank": 0, "hostname": "h", "pid": 1},
        default_dir=str(tmp_path / "early"),
    )
    exporter.tick()
    old = tmp_path / "early" / "telemetry" / "rank0.jsonl"
    assert old.exists()
    telemetry.suggest_out_dir(str(tmp_path / "runs" / "proj"))
    exporter.tick()
    new = tmp_path / "runs" / "proj" / "telemetry" / "rank0.jsonl"
    assert not old.exists(), "split shard history left behind"
    assert [r["seq"] for r in read_shard_file(str(new))] == [0, 1]


# -- identity in forensic surfaces ----------------------------------------


def test_watchdog_report_carries_identity():
    from rocket_tpu.obs.watchdog import Watchdog

    watchdog = Watchdog(deadline_s=60.0)
    watchdog.identity = {"rank": 2, "hostname": "tpu-worker-2", "pid": 99}
    report = watchdog._build_report(stalled_for=120.0)
    assert "process: rank 2 on tpu-worker-2 (pid 99)" in report


def test_flight_manifest_carries_rank_and_hostname(tmp_path):
    from rocket_tpu.obs.flight import FlightRecorder

    class _StubRuntime:
        process_index = 1
        process_count = 4
        is_main_process = True
        project_dir = str(tmp_path)

        def rng_state_dict(self):
            return {}

    telemetry = Telemetry(enabled=True, out_dir=str(tmp_path / "run"))
    recorder = FlightRecorder(telemetry=telemetry, runtime=_StubRuntime())
    bundle = recorder.dump("unit_test")
    assert bundle is not None
    with open(os.path.join(bundle, "blackbox.json"), encoding="utf-8") as f:
        manifest = json.load(f)
    assert manifest["process"]["rank"] == 1
    assert manifest["process"]["hostname"]
    assert manifest["process"]["count"] == 4


def test_supervisor_metrics_endpoint(tmp_path):
    """The supervisor mounts its own /metrics (role="supervisor" label)
    so a restarting fleet keeps one stable scrape target — stdlib-only,
    no backend init (the supervisor must stay signal-safe)."""
    from rocket_tpu.resilience.supervisor import Supervisor

    supervisor = Supervisor(nproc=2, script="train.py", metrics_port=0,
                            state_dir=str(tmp_path))
    supervisor._start_metrics()
    try:
        assert supervisor._metrics_server is not None
        supervisor._publish_metrics()
        url = f"http://127.0.0.1:{supervisor._metrics_server.port}/metrics"
        body = urllib.request.urlopen(url, timeout=5).read().decode()
    finally:
        supervisor._stop_metrics()
    assert 'rocket_tpu_supervisor_restarts{role="supervisor"} 0' in body
    assert 'rocket_tpu_supervisor_generations{role="supervisor"} 0' in body
    assert "rocket_tpu_supervisor_goodput_fraction" in body


# -- the obs CLI: top / watch / report fallback ----------------------------


def _write_fleet(run_dir, ranks=(0, 1)) -> None:
    for rank in ranks:
        ShardWriter(
            os.path.join(run_dir, "telemetry", f"rank{rank}.jsonl")
        ).append(_rank_record(rank, 50.0 - 10 * rank, 100.0,
                              {"le_1e-05": 10}))


def test_obs_top_once_renders_fleet(tmp_path, capsys):
    from rocket_tpu.obs.__main__ import main

    _write_fleet(str(tmp_path))
    assert main(["top", str(tmp_path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "obs top — 2 rank(s)" in out
    assert "host0" in out and "host1" in out
    assert "perf/steps_per_sec" in out
    assert "rank 0" in out  # slowest-rank attribution column
    assert "serve/itl_s" in out
    # No shards at all: usage error, stderr hint.
    assert main(["top", str(tmp_path / "void"), "--once"]) == 2


def test_obs_watch_gates_on_slo(tmp_path, capsys):
    from rocket_tpu.obs.__main__ import main

    _write_fleet(str(tmp_path))
    violating = tmp_path / "tight.json"
    violating.write_text(json.dumps({"version": 1, "slos": [
        {"name": "steps_floor", "kind": "gauge_min",
         "metric": "perf/steps_per_sec", "objective": 1000.0},
    ]}))
    passing = tmp_path / "slack.json"
    passing.write_text(json.dumps({"version": 1, "slos": [
        {"name": "steps_floor", "kind": "gauge_min",
         "metric": "perf/steps_per_sec", "objective": 1.0},
    ]}))
    assert main(["watch", str(tmp_path), "--slo", str(violating)]) == 1
    out = capsys.readouterr().out
    assert "VIOLATION steps_floor (rank 0)" in out
    assert "VIOLATION steps_floor (rank 1)" in out
    assert main(["watch", str(tmp_path), "--slo", str(passing)]) == 0
    assert "all SLOs within objective" in capsys.readouterr().out
    assert main(["watch", str(tmp_path), "--slo",
                 str(tmp_path / "missing.json")]) == 2


def test_obs_report_falls_back_to_shards(tmp_path, capsys):
    """A run dir with no telemetry.json (worker died before DESTROY)
    still reports from its streaming shards."""
    from rocket_tpu.obs.__main__ import main

    solo = tmp_path / "solo"
    _write_fleet(str(solo), ranks=(0,))
    assert main(["report", str(solo)]) == 0
    out = capsys.readouterr().out
    assert "reconstructed from streaming shards" in out
    fleet = tmp_path / "fleet"
    _write_fleet(str(fleet), ranks=(0, 1))
    assert main(["report", str(fleet)]) == 0
    assert "obs top — 2 rank(s)" in capsys.readouterr().out
    assert main(["report", str(tmp_path / "void")]) == 2
