"""Native-layout flash kernels (ops/flash_native.py) vs the XLA paths.

Interpret mode on the virtual CPU mesh — same kernel code the TPU compiles.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from rocket_tpu.nn.attention import (
    MultiHeadAttention,
    apply_rope,
    apply_rope_bthd,
    dot_product_attention,
    grouped_dot_product_attention,
)
from rocket_tpu.ops.flash_native import (
    flash_bthd,
    flash_bthd_sharded,
    flash_fused,
    flash_fused_sharded,
)


def _heads(x2, h):
    """(B, T, H*D) -> (B, H, T, D)."""
    b, t, f = x2.shape
    return x2.reshape(b, t, h, f // h).transpose(0, 2, 1, 3)


def _flat(x4):
    """(B, H, T, D) -> (B, T, H*D)."""
    b, h, t, d = x4.shape
    return x4.transpose(0, 2, 1, 3).reshape(b, t, h * d)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("h", [4, 3])  # even (kb=2 packing) and odd (kb=1)
def test_fused_matches_xla(causal, h):
    b, t, d = 2, 256, 64
    fused = jax.random.normal(jax.random.key(0), (b, t, 3 * h * d))
    q2, k2, v2 = fused[..., :h * d], fused[..., h * d:2 * h * d], fused[..., 2 * h * d:]
    ref = _flat(
        dot_product_attention(
            _heads(q2, h), _heads(k2, h), _heads(v2, h), causal=causal
        )
    )
    out = flash_fused(fused, h, causal=causal, block_q=128, block_k=128)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5


def test_fused_grads_match_xla():
    b, t, h, d = 1, 256, 2, 32
    fused = jax.random.normal(jax.random.key(1), (b, t, 3 * h * d))

    def ref_loss(f):
        q2, k2, v2 = jnp.split(f, 3, axis=-1)
        return (
            dot_product_attention(
                _heads(q2, h), _heads(k2, h), _heads(v2, h), causal=True
            )
            ** 2
        ).sum()

    def fl_loss(f):
        return (flash_fused(f, h, causal=True, block_q=128, block_k=128) ** 2).sum()

    g_ref = jax.grad(ref_loss)(fused)
    g_fl = jax.grad(fl_loss)(fused)
    assert jnp.max(jnp.abs(g_ref - g_fl)) < 1e-4


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("dq_split", [True, False])
def test_fused_grads_match_xla_both_dq_strategies(dq_split, causal):
    """The backward has two dq strategies — the fused f32-partials pass
    (default while the partial buffer fits _DQ_PARTIALS_MAX_BYTES) and
    the split accumulating kernel
    (the memory-bound escape) — both must match XLA. The public dq_split
    kwarg forces each regardless of the nk threshold (t=512 @ block 128 is
    nk=4, which would default to partials)."""
    b, t, h, d = 1, 512, 2, 32
    fused = jax.random.normal(jax.random.key(5), (b, t, 3 * h * d))

    def ref_loss(f):
        q2, k2, v2 = jnp.split(f, 3, axis=-1)
        return (
            dot_product_attention(
                _heads(q2, h), _heads(k2, h), _heads(v2, h), causal=causal
            )
            ** 2
        ).sum()

    def fl_loss(f):
        return (
            flash_fused(
                f, h, causal=causal, block_q=128, block_k=128,
                dq_split=dq_split,
            ) ** 2
        ).sum()

    g_ref = jax.grad(ref_loss)(fused)
    g_fl = jax.grad(fl_loss)(fused)
    assert jnp.max(jnp.abs(g_ref - g_fl)) < 2e-4


@pytest.mark.parametrize("dq_split", [True, False])
def test_bthd_gqa_grads_both_dq_strategies(dq_split):
    b, t, h, h_kv, d = 1, 512, 4, 2, 32
    args = (
        jax.random.normal(jax.random.key(6), (b, t, h * d)),
        jax.random.normal(jax.random.key(7), (b, t, h_kv * d)),
        jax.random.normal(jax.random.key(8), (b, t, h_kv * d)),
    )

    def ref_loss(q2, k2, v2):
        return (
            grouped_dot_product_attention(
                _heads(q2, h), _heads(k2, h_kv), _heads(v2, h_kv), causal=True
            )
            ** 2
        ).sum()

    def fl_loss(q2, k2, v2):
        return (
            flash_bthd(
                q2, k2, v2, h, h_kv, causal=True, block_q=128, block_k=128,
                dq_split=dq_split,
            )
            ** 2
        ).sum()

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(*args)
    g_fl = jax.grad(fl_loss, argnums=(0, 1, 2))(*args)
    for a, b_ in zip(g_ref, g_fl):
        assert jnp.max(jnp.abs(a - b_)) < 2e-4


@pytest.mark.parametrize("h,h_kv", [(6, 2), (4, 1), (4, 4)])
def test_bthd_gqa_matches_grouped_einsum(h, h_kv):
    b, t, d = 2, 256, 32
    q2 = jax.random.normal(jax.random.key(1), (b, t, h * d))
    k2 = jax.random.normal(jax.random.key(2), (b, t, h_kv * d))
    v2 = jax.random.normal(jax.random.key(3), (b, t, h_kv * d))
    ref = _flat(
        grouped_dot_product_attention(
            _heads(q2, h), _heads(k2, h_kv), _heads(v2, h_kv), causal=True
        )
    )
    out = flash_bthd(q2, k2, v2, h, h_kv, causal=True, block_q=128, block_k=128)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5


def test_bthd_gqa_grads_match():
    b, t, h, h_kv, d = 1, 256, 4, 2, 32
    args = (
        jax.random.normal(jax.random.key(1), (b, t, h * d)),
        jax.random.normal(jax.random.key(2), (b, t, h_kv * d)),
        jax.random.normal(jax.random.key(3), (b, t, h_kv * d)),
    )

    def ref_loss(q2, k2, v2):
        return (
            grouped_dot_product_attention(
                _heads(q2, h), _heads(k2, h_kv), _heads(v2, h_kv), causal=True
            )
            ** 2
        ).sum()

    def fl_loss(q2, k2, v2):
        return (
            flash_bthd(q2, k2, v2, h, h_kv, causal=True, block_q=128, block_k=128)
            ** 2
        ).sum()

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(*args)
    g_fl = jax.grad(fl_loss, argnums=(0, 1, 2))(*args)
    for a, b_ in zip(g_ref, g_fl):
        assert jnp.max(jnp.abs(a - b_)) < 1e-4


def test_apply_rope_bthd_matches_bhtd():
    b, h, t, d = 2, 3, 64, 32
    x = jax.random.normal(jax.random.key(0), (b, h, t, d))
    ref = apply_rope(x, offset=5)
    out = apply_rope_bthd(x.transpose(0, 2, 1, 3), offset=5)
    assert jnp.max(jnp.abs(ref - out.transpose(0, 2, 1, 3))) < 1e-6


def test_mha_gqa_flash_matches_xla_grouped():
    """The LAYER's flash GQA route (native kernel, no K/V repeat) equals
    its XLA grouped-einsum route."""
    layer_x = MultiHeadAttention(128, 4, num_kv_heads=2, impl="xla")
    layer_f = MultiHeadAttention(128, 4, num_kv_heads=2, impl="flash")
    params = layer_x.init(jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (2, 256, 128))
    out_x, _ = layer_x.apply(params, x, mode="eval")
    out_f, _ = layer_f.apply(params, x, mode="eval")
    assert jnp.max(jnp.abs(out_x - out_f)) < 1e-5


def test_mha_rope_flash_matches_xla():
    layer_x = MultiHeadAttention(128, 4, rope=True, impl="xla")
    layer_f = MultiHeadAttention(128, 4, rope=True, impl="flash")
    params = layer_x.init(jax.random.key(1))
    x = jax.random.normal(jax.random.key(2), (2, 256, 128))
    out_x, _ = layer_x.apply(params, x, mode="eval")
    out_f, _ = layer_f.apply(params, x, mode="eval")
    assert jnp.max(jnp.abs(out_x - out_f)) < 1e-5


# -- multi-device seam ------------------------------------------------------


def _mesh(shape):
    names = tuple(shape.keys())
    sizes = tuple(shape.values())
    return Mesh(
        np.asarray(jax.devices()[: int(np.prod(sizes))]).reshape(sizes), names
    )


def test_fused_sharded_dp_and_tp_match_xla():
    b, t, h, d = 8, 256, 4, 32
    fused = jax.random.normal(jax.random.key(0), (b, t, 3 * h * d))
    q2, k2, v2 = jnp.split(fused, 3, axis=-1)
    ref = _flat(
        dot_product_attention(
            _heads(q2, h), _heads(k2, h), _heads(v2, h), causal=True
        )
    )
    for shape, spec in [
        ({"data": 8}, P("data", None, None)),
        ({"data": 4, "model": 2}, P("data", None, "model")),
    ]:
        mesh = _mesh(shape)
        placed = jax.device_put(fused, NamedSharding(mesh, spec))

        @jax.jit
        def run(f, mesh=mesh):
            return flash_fused_sharded(
                f, h, causal=True, mesh=mesh, block_q=128, block_k=128
            )

        out = run(placed)
        assert jnp.max(jnp.abs(ref - out)) < 1e-5, shape

        g = jax.jit(jax.grad(lambda f, mesh=mesh: (
            flash_fused_sharded(
                f, h, causal=True, mesh=mesh, block_q=128, block_k=128
            ) ** 2
        ).sum()))(placed)
        g_ref = jax.grad(lambda f: (
            _flat(dot_product_attention(
                *(_heads(p, h) for p in jnp.split(f, 3, axis=-1)), causal=True
            )) ** 2
        ).sum())(fused)
        assert jnp.max(jnp.abs(g - g_ref)) < 1e-4, shape


def test_bthd_sharded_gqa_tp_matches_xla():
    b, t, h, h_kv, d = 8, 256, 4, 2, 32
    mesh = _mesh({"data": 4, "model": 2})
    q2 = jax.random.normal(jax.random.key(1), (b, t, h * d))
    k2 = jax.random.normal(jax.random.key(2), (b, t, h_kv * d))
    v2 = jax.random.normal(jax.random.key(3), (b, t, h_kv * d))
    ref = _flat(
        grouped_dot_product_attention(
            _heads(q2, h), _heads(k2, h_kv), _heads(v2, h_kv), causal=True
        )
    )

    @jax.jit
    def run(q2, k2, v2):
        return flash_bthd_sharded(
            q2, k2, v2, h, h_kv, causal=True, mesh=mesh,
            block_q=128, block_k=128,
        )

    out = run(q2, k2, v2)
    assert jnp.max(jnp.abs(ref - out)) < 1e-5
