"""utils/perf.py: the peak-FLOPs table and its longest-prefix matching."""

from types import SimpleNamespace

import pytest

from rocket_tpu.utils.perf import PEAK_FLOPS, peak_flops


def _device(kind):
    # peak_flops only reads .device_kind — a stub stands in for jax.Device.
    return SimpleNamespace(device_kind=kind)


@pytest.mark.parametrize(
    "kind, expected_key",
    [
        # Longest prefix wins: the lite SKUs must not resolve to the
        # family entry that prefixes them.
        ("TPU v5 lite", "TPU v5 lite"),
        ("TPU v5", "TPU v5"),
        ("TPU v5p", "TPU v5"),
        ("TPU v6 lite", "TPU v6 lite"),
        ("TPU v6e", "TPU v6"),
        ("TPU v6", "TPU v6"),
        ("TPU v7", "TPU v7"),
        ("TPU v7x", "TPU v7"),
        ("TPU v4", "TPU v4"),
    ],
)
def test_longest_prefix_device_kind_matching(kind, expected_key):
    assert peak_flops(_device(kind)) == PEAK_FLOPS[expected_key]


def test_unknown_kind_returns_none():
    # Callers must omit MFU rather than divide by a wrong peak.
    assert peak_flops(_device("cpu")) is None
    assert peak_flops(_device("TPU v3")) is None


def test_new_generations_present_and_ordered():
    # The v6/v7 entries exist and peaks are monotone across generations.
    assert PEAK_FLOPS["TPU v6"] >= PEAK_FLOPS["TPU v5"]
    assert PEAK_FLOPS["TPU v7"] > PEAK_FLOPS["TPU v6"]
    # v5 lite < v5 (the prefix pair the matcher exists for).
    assert PEAK_FLOPS["TPU v5 lite"] < PEAK_FLOPS["TPU v5"]
