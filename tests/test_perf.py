"""utils/perf.py: the peak-FLOPs / roofline tables and their
longest-prefix matching."""

from types import SimpleNamespace

import pytest

from rocket_tpu.utils.perf import (
    DEVICE_SPECS,
    PEAK_FLOPS,
    DeviceSpec,
    device_spec,
    peak_flops,
)


def _device(kind):
    # peak_flops only reads .device_kind — a stub stands in for jax.Device.
    return SimpleNamespace(device_kind=kind)


@pytest.mark.parametrize(
    "kind, expected_key",
    [
        # Longest prefix wins: the lite SKUs must not resolve to the
        # family entry that prefixes them.
        ("TPU v5 lite", "TPU v5 lite"),
        ("TPU v5", "TPU v5"),
        ("TPU v5p", "TPU v5"),
        ("TPU v6 lite", "TPU v6 lite"),
        ("TPU v6e", "TPU v6"),
        ("TPU v6", "TPU v6"),
        ("TPU v7", "TPU v7"),
        ("TPU v7x", "TPU v7"),
        ("TPU v4", "TPU v4"),
    ],
)
def test_longest_prefix_device_kind_matching(kind, expected_key):
    assert peak_flops(_device(kind)) == PEAK_FLOPS[expected_key]


def test_unknown_kind_returns_none():
    # Callers must omit MFU rather than divide by a wrong peak.
    assert peak_flops(_device("cpu")) is None
    assert peak_flops(_device("TPU v3")) is None


def test_new_generations_present_and_ordered():
    # The v6/v7 entries exist and peaks are monotone across generations.
    assert PEAK_FLOPS["TPU v6"] >= PEAK_FLOPS["TPU v5"]
    assert PEAK_FLOPS["TPU v7"] > PEAK_FLOPS["TPU v6"]
    # v5 lite < v5 (the prefix pair the matcher exists for).
    assert PEAK_FLOPS["TPU v5 lite"] < PEAK_FLOPS["TPU v5"]


def test_device_spec_matches_peak_table_and_prefix_rules():
    # Every roofline entry's bf16 peak agrees with PEAK_FLOPS, and the
    # same longest-prefix matching applies ("TPU v5 lite" not "TPU v5").
    for kind, spec in DEVICE_SPECS.items():
        assert spec.flops_bf16 == PEAK_FLOPS[kind]
        assert spec.kind == kind
    assert device_spec(_device("TPU v5 lite")).kind == "TPU v5 lite"
    assert device_spec("TPU v5p").kind == "TPU v5"
    assert device_spec("TPU v6e").kind == "TPU v6"


def test_device_spec_accepts_kind_string_and_rejects_unknown():
    # The static auditors price hardware that is not present: the kind
    # string is a first-class lookup; unknown kinds return None so the
    # roofline is skipped, never priced against the wrong machine.
    spec = device_spec("TPU v4")
    assert spec.hbm_bw > 0 and spec.ici_bw > 0 and spec.vmem_bytes > 0
    assert device_spec("cpu") is None
    assert device_spec("TPU v3") is None


def test_ridge_points_are_physical():
    # Ridge = peak FLOPs / HBM bandwidth: every TPU generation sits in
    # the hundreds of FLOPs/byte; bandwidth grows with the peak.
    for spec in DEVICE_SPECS.values():
        assert 100 < spec.ridge < 1000
    assert DEVICE_SPECS["TPU v7"].hbm_bw > DEVICE_SPECS["TPU v4"].hbm_bw


def test_hbm_capacity_is_physical():
    # The serving auditor's RKT603 fit check budgets against hbm_bytes:
    # every entry carries a published per-chip capacity (8 GiB .. 256
    # GiB), and the known SKU facts hold (v5e 16 GiB, v5p 95 GiB, v7
    # the largest).
    for spec in DEVICE_SPECS.values():
        assert (8 << 30) <= spec.hbm_bytes <= (256 << 30)
    assert DEVICE_SPECS["TPU v5 lite"].hbm_bytes == 16 << 30
    assert DEVICE_SPECS["TPU v5"].hbm_bytes == 95 << 30
    assert DEVICE_SPECS["TPU v7"].hbm_bytes == max(
        s.hbm_bytes for s in DEVICE_SPECS.values()
    )


def test_ici_link_bandwidth_rows_are_physical():
    # The schedule auditor prices explicit ppermute ring hops against
    # ONE link's bandwidth (a bulk collective drives every link at
    # once): each row's link bandwidth divides the aggregate by the
    # generation's link count — 2D tori (v5e/v6e) 4 links, 3D tori
    # (v4/v5p/v7) 6 — and never exceeds the aggregate.
    for spec in DEVICE_SPECS.values():
        assert 0 < spec.ici_link_bw <= spec.ici_bw
        links = spec.ici_bw / spec.ici_link_bw
        assert 3.5 <= links <= 6.5, (spec.kind, links)
    assert DEVICE_SPECS["TPU v5 lite"].ici_link_bw == 50e9
    assert DEVICE_SPECS["TPU v5"].ici_link_bw == 100e9
    assert DEVICE_SPECS["TPU v7"].ici_link_bw == 200e9


def test_dcn_bandwidth_rows_present():
    # Cross-slice collectives (multi-slice data parallelism) price
    # against per-chip DCN egress: far below ICI on every generation,
    # and newer generations don't regress.
    for spec in DEVICE_SPECS.values():
        assert 0 < spec.dcn_bw < spec.ici_link_bw
    assert DEVICE_SPECS["TPU v5 lite"].dcn_bw == 25e9
    assert DEVICE_SPECS["TPU v7"].dcn_bw >= DEVICE_SPECS["TPU v4"].dcn_bw


def test_ad_hoc_spec_defaults_link_bandwidth():
    # A user-constructed spec without the link column falls back to a
    # 4-link split so hop pricing never divides by zero.
    spec = DeviceSpec("TPU vX", 1e15, 1e12, 4e11, 1 << 20)
    assert spec.ici_link_bw == pytest.approx(1e11)
