"""Test configuration: run every test on a virtual 8-device CPU mesh.

SURVEY §4: multi-device without a cluster —
``--xla_force_host_platform_device_count=8`` exercises the real
pjit/sharding/collective paths on fake CPU devices. Must be set before jax
initializes a backend, hence at conftest import time.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

# This image pre-imports parts of jax at interpreter startup (the env vars
# above would be read too late), so force the platform through the config too.
jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture
def runtime(tmp_path):
    from rocket_tpu.runtime.context import Runtime

    return Runtime(seed=0, project_dir=str(tmp_path))


@pytest.fixture
def runtime8(tmp_path):
    """Runtime over all 8 virtual devices on a data axis."""
    from rocket_tpu.runtime.context import Runtime

    return Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path)
    )


def pytest_addoption(parser):
    parser.addoption(
        "--runslow", action="store_true", default=False,
        help="also run @pytest.mark.slow tests (the full CI tier; the "
        "default fast tier finishes in a few minutes)",
    )


def pytest_configure(config):
    assert len(jax.devices()) == 8, (
        f"expected 8 virtual CPU devices, got {len(jax.devices())}: "
        f"{jax.devices()}"
    )
    config.addinivalue_line(
        "markers",
        "slow: long-running test (multi-process spawns, big compiles); "
        "skipped unless --runslow",
    )


def pytest_collection_modifyitems(config, items):
    if config.getoption("--runslow"):
        return
    skip = pytest.mark.skip(reason="slow tier: pass --runslow")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)
