import pytest

from rocket_tpu import Attributes, Capsule, Dispatcher, Events
from rocket_tpu.utils.probe import Probe


def test_dispatch_routes_to_handler(runtime):
    trace = []
    probe = Probe("p", trace, runtime=runtime)
    probe.bind(runtime)
    attrs = Attributes()
    for event in (Events.SETUP, Events.SET, Events.LAUNCH, Events.RESET, Events.DESTROY):
        probe.dispatch(event, attrs)
    assert [e for _, e in trace] == ["setup", "set", "launch", "reset", "destroy"]


def test_probe_records_timing_and_mode(runtime):
    """Probe entries stay ==(name, event) tuples AND carry a monotonic
    timestamp + the attrs.mode in force, so ordering, timing and mode
    plumbing assert through one instrument."""
    trace = []
    probe = Probe("p", trace, runtime=runtime)
    attrs = Attributes()
    attrs.mode = "train"
    probe.dispatch(Events.SET, attrs)
    probe.dispatch(Events.LAUNCH, attrs)
    attrs.mode = "eval"
    probe.dispatch(Events.LAUNCH, attrs)
    probe.dispatch(Events.RESET, None)

    assert trace == [("p", "set"), ("p", "launch"), ("p", "launch"),
                     ("p", "reset")]
    # Timestamps are monotonic non-decreasing perf_counter captures.
    times = [e.t for e in trace]
    assert times == sorted(times)
    assert trace[1].t > trace[0].t
    # attrs.mode rides each record; None when no attrs were passed.
    assert [e.mode for e in trace] == ["train", "train", "eval", None]
    assert trace[0].name == "p" and trace[0].event == "set"


def test_dispatch_rejects_non_event(runtime):
    capsule = Capsule(runtime=runtime)
    with pytest.raises(RuntimeError):
        capsule.dispatch("launch")


def test_priority_ordering_stable(runtime):
    # Higher priority runs earlier; ties keep construction order
    # (verified reference behavior, dispatcher.py:18-20).
    trace = []
    children = [
        Probe("low", trace, priority=1),
        Probe("first_default", trace),
        Probe("second_default", trace),
        Probe("high", trace, priority=2000),
    ]
    d = Dispatcher(children, runtime=runtime)
    d.launch(Attributes())
    assert [n for n, _ in trace] == ["high", "first_default", "second_default", "low"]


def test_destroy_reversed(runtime):
    trace = []
    d = Dispatcher([Probe("a", trace), Probe("b", trace)], runtime=runtime)
    attrs = Attributes()
    d.setup(attrs)
    trace.clear()
    d.destroy(attrs)
    assert [n for n, _ in trace] == ["b", "a"]


def test_checkpoint_stack_lifo(runtime):
    a = Probe("a", statefull=True, runtime=runtime)
    b = Probe("b", statefull=True, runtime=runtime)
    attrs = Attributes()
    a.setup(attrs)
    b.setup(attrs)
    assert runtime.checkpoint_stack == (a, b)
    b.destroy(attrs)
    a.destroy(attrs)
    assert runtime.checkpoint_stack == ()


def test_checkpoint_stack_out_of_order_destroy_raises(runtime):
    a = Probe("a", statefull=True, runtime=runtime)
    b = Probe("b", statefull=True, runtime=runtime)
    a.setup(Attributes())
    b.setup(Attributes())
    with pytest.raises(RuntimeError, match="stack corrupted"):
        a.destroy(Attributes())


def test_double_registration_raises(runtime):
    a = Probe("a", statefull=True, runtime=runtime)
    a.setup(Attributes())
    with pytest.raises(RuntimeError, match="twice"):
        runtime.register_for_checkpointing(a)


def test_setup_without_runtime_raises():
    with pytest.raises(RuntimeError, match="no runtime"):
        Capsule(statefull=True).setup(Attributes())


def test_guard_rejects_non_capsule(runtime):
    with pytest.raises(RuntimeError, match="not a Capsule"):
        Dispatcher([object()], runtime=runtime)


def test_repr_renders_tree(runtime):
    d = Dispatcher([Probe("a", []), Dispatcher([Probe("b", [])])], runtime=runtime)
    text = repr(d)
    assert "Dispatcher(" in text
    assert "Probe" in text


def test_rebind_different_runtime_raises(runtime, tmp_path):
    from rocket_tpu.runtime.context import Runtime

    capsule = Capsule(runtime=runtime)
    capsule.bind(runtime)  # idempotent
    with pytest.raises(RuntimeError, match="different runtime"):
        capsule.bind(Runtime(project_dir=str(tmp_path)))
