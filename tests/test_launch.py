"""python -m rocket_tpu.launch: spawns N coordinated processes."""

import pytest
import os
import subprocess
import sys


@pytest.mark.slow
def test_launch_two_processes(tmp_path):
    # Same backend gate as the test_multiprocess spawn tests: skip when
    # this jax build's CPU backend can't run cross-process collectives.
    from tests.test_multiprocess import _require_multiprocess_backend

    _require_multiprocess_backend()
    script = tmp_path / "worker.py"
    script.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import sys, os\n"
        f"sys.path.insert(0, {os.getcwd()!r})\n"
        "from rocket_tpu.runtime.context import Runtime\n"
        "runtime = Runtime(seed=0)\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "runtime.wait_for_everyone()\n"
        # ONE atomic write: the child's Gloo threads write to the merged
        # stdout concurrently and can interleave between print()'s several
        # small writes, splitting the token across lines.
        "sys.stdout.write(f'WORKER-{runtime.process_index}-OK\\n')\n"
        "sys.stdout.flush()\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Small per-process mesh + the distributed-init retry budget the proven
    # two-process test uses (connect retries can run minutes under load).
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "-n", "2", str(script)],
        env=env, cwd=os.getcwd(), capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    # Don't require prefix adjacency — C++ log lines from the children can
    # share a line with the token; the token itself is written atomically.
    assert "WORKER-0-OK" in out.stdout, out.stdout
    assert "WORKER-1-OK" in out.stdout, out.stdout
    assert "[rank 0]" in out.stdout and "[rank 1]" in out.stdout


@pytest.mark.slow
def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)\n")
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "-n", "2", str(script)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode != 0


def test_coordinator_error_signatures():
    """The port-race retry fires only on worker output carrying a
    distributed-init FAILURE signature (round-4 advisor: the regex was
    untested). Representative lines from jax's coordination-service stack
    must match; benign progress lines and ordinary user failures must not."""
    from rocket_tpu.launch import _COORDINATOR_ERROR_RE as sig

    failures = [
        # grpc server bind failure surfaced through jax.distributed.initialize
        "RuntimeError: Failed to start coordination service: UNKNOWN: "
        "Could not start gRPC server: Address already in use",
        "E0000 00:00:00.0 server_chttp2.cc:40] {\"description\":\"Failed to "
        "bind to address\",\"os_error\":\"Address already in use\"}",
        # worker-side connect failures
        "absl::Status DEADLINE_EXCEEDED: Failed to connect to coordination "
        "service after 300s",
        "RuntimeError: Unable to connect to the coordinator at "
        "127.0.0.1:43211",
        "XlaRuntimeError: UNAVAILABLE: coordination service is unavailable; "
        "connection refused",
        "coordinator at 127.0.0.1:5005 timed out",
        "Error starting coordination service: port in use",
    ]
    for line in failures:
        assert sig.search(line), f"must match: {line!r}"

    benign = [
        "Connecting to JAX distributed service on 127.0.0.1:43211",
        "I0000 coordination service started on port 43211",
        "Coordination service successfully connected all 2 processes",
        "ImportError: No module named 'mymodel'",
        "AssertionError: expected 4 processes",
        "ValueError: bad learning rate",
        "loss=2.31 step=10",
    ]
    for line in benign:
        assert not sig.search(line), f"must NOT match: {line!r}"


@pytest.mark.slow
def test_launch_tears_down_stragglers(tmp_path):
    """When one rank dies, the launcher must terminate the survivors and
    exit non-zero rather than hang on a sequential wait."""
    import time

    script = tmp_path / "split.py"
    script.write_text(
        "import os, sys, time\n"
        "if os.environ['JAX_PROCESS_ID'] == '1':\n"
        "    sys.exit(5)\n"
        "time.sleep(600)\n"  # rank 0 'hangs in a collective'
    )
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "-n", "2", str(script)],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode != 0
    assert time.time() - t0 < 60  # did not wait out rank 0's sleep
