"""python -m rocket_tpu.launch: spawns N coordinated processes; with
--supervise, restarts crashed generations and drains on SIGTERM."""

import json
import pytest
import os
import subprocess
import sys


@pytest.mark.slow
def test_launch_two_processes(tmp_path):
    # Same backend gate as the test_multiprocess spawn tests: skip when
    # this jax build's CPU backend can't run cross-process collectives.
    from tests.test_multiprocess import _require_multiprocess_backend

    _require_multiprocess_backend()
    script = tmp_path / "worker.py"
    script.write_text(
        "import jax\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import sys, os\n"
        f"sys.path.insert(0, {os.getcwd()!r})\n"
        "from rocket_tpu.runtime.context import Runtime\n"
        "runtime = Runtime(seed=0)\n"
        "assert jax.process_count() == 2, jax.process_count()\n"
        "runtime.wait_for_everyone()\n"
        # ONE atomic write: the child's Gloo threads write to the merged
        # stdout concurrently and can interleave between print()'s several
        # small writes, splitting the token across lines.
        "sys.stdout.write(f'WORKER-{runtime.process_index}-OK\\n')\n"
        "sys.stdout.flush()\n"
    )
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # Small per-process mesh + the distributed-init retry budget the proven
    # two-process test uses (connect retries can run minutes under load).
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "-n", "2", str(script)],
        env=env, cwd=os.getcwd(), capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    # Don't require prefix adjacency — C++ log lines from the children can
    # share a line with the token; the token itself is written atomically.
    assert "WORKER-0-OK" in out.stdout, out.stdout
    assert "WORKER-1-OK" in out.stdout, out.stdout
    assert "[rank 0]" in out.stdout and "[rank 1]" in out.stdout


@pytest.mark.slow
def test_launch_propagates_failure(tmp_path):
    script = tmp_path / "bad.py"
    script.write_text("import sys; sys.exit(3)\n")
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "-n", "2", str(script)],
        capture_output=True, text=True, timeout=60,
    )
    assert out.returncode != 0


def test_coordinator_error_signatures():
    """The port-race retry fires only on worker output carrying a
    distributed-init FAILURE signature (round-4 advisor: the regex was
    untested). Representative lines from jax's coordination-service stack
    must match; benign progress lines and ordinary user failures must not."""
    from rocket_tpu.launch import _COORDINATOR_ERROR_RE as sig

    failures = [
        # grpc server bind failure surfaced through jax.distributed.initialize
        "RuntimeError: Failed to start coordination service: UNKNOWN: "
        "Could not start gRPC server: Address already in use",
        "E0000 00:00:00.0 server_chttp2.cc:40] {\"description\":\"Failed to "
        "bind to address\",\"os_error\":\"Address already in use\"}",
        # worker-side connect failures
        "absl::Status DEADLINE_EXCEEDED: Failed to connect to coordination "
        "service after 300s",
        "RuntimeError: Unable to connect to the coordinator at "
        "127.0.0.1:43211",
        "XlaRuntimeError: UNAVAILABLE: coordination service is unavailable; "
        "connection refused",
        "coordinator at 127.0.0.1:5005 timed out",
        "Error starting coordination service: port in use",
    ]
    for line in failures:
        assert sig.search(line), f"must match: {line!r}"

    benign = [
        "Connecting to JAX distributed service on 127.0.0.1:43211",
        "I0000 coordination service started on port 43211",
        "Coordination service successfully connected all 2 processes",
        "ImportError: No module named 'mymodel'",
        "AssertionError: expected 4 processes",
        "ValueError: bad learning rate",
        "loss=2.31 step=10",
    ]
    for line in benign:
        assert not sig.search(line), f"must NOT match: {line!r}"


@pytest.mark.slow
def test_launch_tears_down_stragglers(tmp_path):
    """When one rank dies, the launcher must terminate the survivors and
    exit non-zero rather than hang on a sequential wait."""
    import time

    script = tmp_path / "split.py"
    script.write_text(
        "import os, sys, time\n"
        "if os.environ['JAX_PROCESS_ID'] == '1':\n"
        "    sys.exit(5)\n"
        "time.sleep(600)\n"  # rank 0 'hangs in a collective'
    )
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "-n", "2", str(script)],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode != 0
    assert time.time() - t0 < 60  # did not wait out rank 0's sleep


@pytest.mark.slow
def test_launch_kills_sigterm_ignoring_straggler(tmp_path):
    """Straggler teardown must escalate SIGTERM -> SIGKILL after the
    bounded --term-grace: a worker that installs SIG_IGN for SIGTERM
    (or is wedged in a collective, same observable) cannot hang the
    launcher forever."""
    import time

    script = tmp_path / "stubborn.py"
    script.write_text(
        "import os, signal, sys, time\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "if os.environ['JAX_PROCESS_ID'] == '1':\n"
        "    time.sleep(1)\n"
        "    sys.exit(5)\n"
        "time.sleep(600)\n"  # rank 0 ignores the TERM and 'hangs'
    )
    t0 = time.time()
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "-n", "2",
         "--term-grace", "2", str(script)],
        capture_output=True, text=True, timeout=120,
    )
    assert out.returncode != 0
    assert time.time() - t0 < 60  # TERM grace + KILL, not rank 0's sleep


@pytest.mark.slow
def test_worker_initiated_drain_releases_blocked_peers(tmp_path):
    """A rank exiting EXIT_DRAINED on its own (a per-rank preemption
    notice) must start the SIGTERM-forward + drain-grace clock for its
    peers: a peer blocked in a collective waiting for the drained rank
    would otherwise hang wait() forever (EXIT_DRAINED sets neither
    failure_rc nor, by itself, any deadline)."""
    import time

    from rocket_tpu.launch import WorkerGroup
    from rocket_tpu.resilience.faults import EXIT_DRAINED

    script = tmp_path / "split_drain.py"
    script.write_text(
        "import os, signal, sys, time\n"
        "if os.environ['JAX_PROCESS_ID'] == '1':\n"
        "    time.sleep(0.5)\n"
        f"    sys.exit({EXIT_DRAINED})\n"
        "signal.signal(signal.SIGTERM, signal.SIG_IGN)\n"
        "time.sleep(600)\n"  # rank 0 'wedged in a collective'
    )
    group = WorkerGroup(2, str(script), term_grace_s=2.0)
    group.spawn()
    t0 = time.time()
    rc, codes = group.wait(drain_grace_s=2.0)
    assert time.time() - t0 < 60  # grace + TERM->KILL, not rank 0's sleep
    assert codes[1] == EXIT_DRAINED
    assert rc != 0  # the wedged peer could not drain: not a clean stop


def test_plain_launch_passes_drain_grace_to_wait(monkeypatch):
    """--drain-grace must reach WorkerGroup.wait in PLAIN mode too: a
    worker-initiated drain's peer-teardown deadline is the flag the user
    set, not the hardcoded 60 s default (regression: _run_once used to
    call wait() with no drain_grace_s)."""
    import argparse

    import rocket_tpu.launch as launch

    seen = {}
    monkeypatch.setattr(launch.WorkerGroup, "spawn", lambda self: None)

    def fake_wait(self, drain_event=None, drain_grace_s=60.0, on_poll=None):
        seen["drain_grace_s"] = drain_grace_s
        return 0, [0]

    monkeypatch.setattr(launch.WorkerGroup, "wait", fake_wait)
    monkeypatch.setattr(launch.WorkerGroup, "teardown", lambda self: None)
    args = argparse.Namespace(
        nproc=1, script="train.py", script_args=[],
        term_grace=10.0, drain_grace=7.5,
    )
    rc, _ = launch._run_once(args, port=45555)
    assert rc == 0
    assert seen["drain_grace_s"] == 7.5


@pytest.mark.slow
def test_supervised_launch_restarts_until_success(tmp_path):
    """--supervise: a generation-0 crash is an event, not a verdict —
    the worker is relaunched (with ROCKET_TPU_GENERATION advanced) and
    the clean second generation ends the run with exit 0 and a
    supervisor.json recording the restart."""
    script = tmp_path / "flaky.py"
    script.write_text(
        "import os, sys\n"
        "sys.exit(3 if os.environ['ROCKET_TPU_GENERATION'] == '0' else 0)\n"
    )
    state_dir = tmp_path / "state"
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "--supervise", "-n", "1",
         "--backoff", "0.05", "--progress-grace", "0.01",
         "--state-dir", str(state_dir), str(script)],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    state = json.loads((state_dir / "supervisor.json").read_text())
    assert state["outcome"] == "completed"
    assert state["restarts"] == 1
    assert [g["outcome"] for g in state["generations"]] == [
        "crashed", "completed"]
    assert 0.0 <= state["goodput_fraction"] <= 1.0


@pytest.mark.slow
def test_supervised_launch_honors_drained_worker(tmp_path):
    """A worker exiting the distinguished drained code is a CLEAN stop:
    the supervisor exits 0 without restarting."""
    from rocket_tpu.resilience import EXIT_DRAINED

    script = tmp_path / "drainer.py"
    script.write_text(f"import sys; sys.exit({EXIT_DRAINED})\n")
    state_dir = tmp_path / "state"
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "--supervise", "-n", "1",
         "--state-dir", str(state_dir), str(script)],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    state = json.loads((state_dir / "supervisor.json").read_text())
    assert state["outcome"] == "drained"
    assert state["restarts"] == 0
    assert state["generations"][0]["exit_codes"] == [EXIT_DRAINED]


@pytest.mark.slow
def test_supervised_launch_crash_loop_gives_up(tmp_path):
    """A deterministic crasher must not be restarted forever: after the
    crash-loop threshold the supervisor refuses to thrash, exits
    non-zero, and supervisor.json carries the failing output tail."""
    script = tmp_path / "dead.py"
    script.write_text("import sys; print('boom-trail'); sys.exit(9)\n")
    state_dir = tmp_path / "state"
    out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.launch", "--supervise", "-n", "1",
         "--backoff", "0.05", "--crash-loop", "2", "--progress-grace", "1e9",
         "--state-dir", str(state_dir), str(script)],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode != 0
    state = json.loads((state_dir / "supervisor.json").read_text())
    assert state["outcome"] == "crash_loop"
    assert len(state["generations"]) == 2
    tail = state["generations"][-1]["output_tail"]
    assert any("boom-trail" in line for line in tail["0"])
