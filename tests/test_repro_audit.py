"""Unit tests for the determinism auditor (RKT9xx).

The CLI-level contract (targets list, budget gate, badrepro demo,
`analysis all`) lives in tests/test_analysis_cli.py; this file exercises
the building blocks in-process: the PRNG-key provenance walker, the
jaxpr-level nondeterministic-scatter scan, the canonical fingerprints,
the string-valued (fingerprint) branch of the budget differ, and the
replay sentinel.
"""

import jax
import jax.numpy as jnp
import pytest

from rocket_tpu.analysis.budgets import REPRO_GATED_KEYS, diff_budget
from rocket_tpu.analysis.repro_audit import (
    analyze_key_provenance,
    hlo_fingerprint,
    jaxpr_fingerprint,
    run_replay_sentinel,
    scan_nondet_jaxpr,
)
from rocket_tpu.analysis.rules.repro_rules import (
    check_key_reuse,
    check_nondet_hlo,
)


def key_findings(fn, *args):
    flow = analyze_key_provenance(jax.make_jaxpr(fn)(*args))
    return check_key_reuse(flow.consumptions, flow.unfolded), flow


# -- RKT901: key-provenance walker -------------------------------------------


def test_key_reuse_fires_on_double_consumption():
    def step(key, x):
        a = jax.random.normal(key, x.shape)
        b = jax.random.uniform(key, x.shape)  # same key value again
        return x + a * b

    findings, flow = key_findings(step, jax.random.key(0), jnp.ones(4))
    assert [f.rule for f in findings] == ["RKT901"]
    assert "consumed by 2" in findings[0].message
    assert flow.n_consumers == 2


def test_split_keys_are_clean():
    def step(key, x):
        k1, k2 = jax.random.split(key)
        return x + jax.random.normal(k1, x.shape) * jax.random.uniform(
            k2, x.shape
        )

    findings, flow = key_findings(step, jax.random.key(0), jnp.ones(4))
    assert findings == []
    assert flow.n_derivations >= 1


def test_unfolded_loop_key_fires():
    # The closure key enters the scan body unchanged: every iteration
    # draws the SAME noise — the classic silent-correlation bug.
    def step(key, xs):
        def body(acc, x):
            return acc + jax.random.normal(key, x.shape) * x, None

        acc, _ = jax.lax.scan(body, jnp.zeros(4), xs)
        return acc

    findings, flow = key_findings(step, jax.random.key(0), jnp.ones((3, 4)))
    assert any("loop" in f.message for f in findings), [
        f.message for f in findings
    ]
    assert all(f.rule == "RKT901" for f in findings)
    assert flow.unfolded


def test_fold_in_with_loop_carry_is_clean():
    def step(key, xs):
        def body(carry, x):
            i, acc = carry
            k = jax.random.fold_in(key, i)
            return (i + 1, acc + jax.random.normal(k, x.shape) * x), None

        (_, acc), _ = jax.lax.scan(body, (0, jnp.zeros(4)), xs)
        return acc

    findings, _ = key_findings(step, jax.random.key(0), jnp.ones((3, 4)))
    assert findings == []


def test_cond_branches_do_not_double_count():
    # Only ONE branch executes per call: feeding the same key to both
    # branches of a cond is a single consumption, not reuse.
    def step(pred, key, x):
        return jax.lax.cond(
            pred,
            lambda k: jax.random.normal(k, x.shape),
            lambda k: jax.random.uniform(k, x.shape),
            key,
        )

    findings, _ = key_findings(
        step, jnp.bool_(True), jax.random.key(0), jnp.ones(4)
    )
    assert findings == []


# -- RKT902: nondeterministic-scatter scan (jaxpr level) ---------------------


def test_float_scatter_add_without_unique_indices_fires():
    def grad_like(table, idx, upd):
        return table.at[idx].add(upd)

    closed = jax.make_jaxpr(grad_like)(
        jnp.zeros(8), jnp.array([1, 1, 2]), jnp.ones(3)
    )
    ops = scan_nondet_jaxpr(closed)
    assert len(ops) == 1 and ops[0][0] == "scatter"
    assert check_nondet_hlo(ops)[0].rule == "RKT902"


def test_unique_indices_and_int_scatters_are_clean():
    def unique(table, idx, upd):
        return table.at[idx].add(upd, unique_indices=True)

    def integer(table, idx, upd):
        return table.at[idx].add(upd)

    assert scan_nondet_jaxpr(jax.make_jaxpr(unique)(
        jnp.zeros(8), jnp.array([1, 2, 3]), jnp.ones(3)
    )) == []
    # Integer accumulation is associative bit-for-bit: not flagged.
    assert scan_nondet_jaxpr(jax.make_jaxpr(integer)(
        jnp.zeros(8, jnp.int32), jnp.array([1, 1]),
        jnp.ones(2, jnp.int32),
    )) == []


def test_scatter_allowlist_matches_source_site():
    ops = [(
        "scatter",
        "scatter-add@rocket_tpu/models/transformer.py:998 (embed_lookup)",
        "unique_indices=False (traced program)",
    )]
    assert check_nondet_hlo(ops, scatter_allow=()) != []
    assert check_nondet_hlo(
        ops, scatter_allow=("rocket_tpu/models/transformer.py",)
    ) == []


# -- canonical fingerprints --------------------------------------------------


def fn_a(x):
    return jnp.tanh(x) * 2.0


def fn_b(x):
    return jnp.sin(x) + 1.0


def test_jaxpr_fingerprint_is_stable_and_discriminating():
    x = jnp.ones((4, 4))
    fp1 = jaxpr_fingerprint(jax.make_jaxpr(fn_a)(x))
    fp2 = jaxpr_fingerprint(jax.make_jaxpr(fn_a)(x))
    assert fp1 == fp2 and len(fp1) == 16
    assert fp1 != jaxpr_fingerprint(jax.make_jaxpr(fn_b)(x))


def test_hlo_fingerprint_is_stable_and_discriminating():
    x = jnp.ones((4, 4))
    hlo_a1 = jax.jit(fn_a).lower(x).compile().as_text()
    hlo_a2 = jax.jit(fn_a).lower(x).compile().as_text()
    hlo_b = jax.jit(fn_b).lower(x).compile().as_text()
    assert hlo_fingerprint(hlo_a1) == hlo_fingerprint(hlo_a2)
    assert hlo_fingerprint(hlo_a1) != hlo_fingerprint(hlo_b)


# -- RKT906: the fingerprint (string) branch of the budget differ ------------


def test_diff_budget_gates_fingerprints_on_exact_equality():
    committed = {"program_fingerprint": "a" * 16, "random_consumers": 3}
    kwargs = dict(
        keys=REPRO_GATED_KEYS, rule="RKT906", family="repro"
    )
    assert diff_budget("t", committed, dict(committed), **kwargs) == []
    drifted = dict(committed, program_fingerprint="b" * 16)
    findings = diff_budget("t", committed, drifted, **kwargs)
    assert [f.rule for f in findings] == ["RKT906"]
    assert "program_fingerprint" in findings[0].message
    assert "--update-budgets" in findings[0].message


# -- RKT905: replay sentinel -------------------------------------------------


@pytest.mark.slow
def test_replay_sentinel_is_bitwise_equal():
    # The non-slow CLI test already proves this end-to-end through
    # `analysis repro --target gpt2_sentinel`; this is the in-process
    # leg so a sentinel regression pinpoints the helper, not the CLI.
    mismatches, n_leaves = run_replay_sentinel()
    assert mismatches == []
    assert n_leaves > 0
