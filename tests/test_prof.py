"""obs.prof + analysis.calib — device-trace capture, parsing and the
measured-vs-predicted reconcile join (ISSUE 13).

Three layers, bottom-up:

* parser units over synthetic trace events and the checked-in CPU
  capture (``tests/fixtures/prof/perfetto_trace.json.gz`` — three
  ``StepTraceAnnotation("train")`` steps of a tiny jitted matmul step,
  captured by ``jax.profiler`` with ``create_perfetto_trace=True``):
  slice bucketing, step-annotation alignment, category mapping,
  exposed-comm interval math, canonicalization;
* the reconcile join against a FAKE priced DAG (hand-built
  ``sched_audit.OpCost`` rows): name join, per-device comparand,
  category refinement by priced kind, signed error math, coverage, and
  the RKT701/702/703 gates;
* the process contract: ``python -m rocket_tpu.obs prof`` on the
  fixture, the Profiler capsule's ``ROCKET_TPU_PROF`` policy, the serve
  engine's ``capture_trace`` window validation, and (one live leg) the
  ``analysis calib`` CLI's capture->parse->reconcile e2e with the
  committed budgets.
"""

import gzip
import json
import os
import subprocess
import sys

import pytest

from rocket_tpu.analysis.rules.calib_rules import (
    check_error_ceiling,
    check_join_coverage,
)
from rocket_tpu.obs.prof import (
    COLLECTIVE_OPS,
    ProfPolicy,
    canonical_op_name,
    categorize,
    find_trace_file,
    load_trace_events,
    opcode_of,
    parse_step_window,
    parse_trace,
    prof_record,
    publish_prof,
    render_prof,
)
from rocket_tpu.obs.registry import MetricsRegistry, estimate_quantiles

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURE_TRACE = os.path.join(
    REPO, "tests", "fixtures", "prof", "perfetto_trace.json.gz"
)
CALIB_BUDGETS = os.path.join(REPO, "tests", "fixtures", "budgets", "calib")
DRIFTED_BUDGETS = os.path.join(
    REPO, "tests", "fixtures", "budgets", "calib_drifted"
)


# -- capture policy ----------------------------------------------------------

def test_policy_env_grammar():
    assert ProfPolicy.from_env(None) is None
    assert ProfPolicy.from_env("0") is None
    assert ProfPolicy.from_env("") is None
    assert ProfPolicy.from_env("off") is None
    assert ProfPolicy.from_env("1") == ProfPolicy()
    assert ProfPolicy.from_env("5:9") == ProfPolicy(steps=4, every=0,
                                                    start=5)
    assert ProfPolicy.from_env("3@200") == ProfPolicy(steps=3, every=200,
                                                      start=200)


@pytest.mark.parametrize("bad", ["junk", "5:5", "3:1", "0@3", "5@3",
                                 "-1:4"])
def test_policy_rejects_malformed_values(bad):
    with pytest.raises(ValueError):
        ProfPolicy.from_env(bad)


def test_policy_window_starts():
    periodic = ProfPolicy(steps=2, every=100, start=100)
    assert [s for s in range(401) if periodic.window_start(s)] == [
        100, 200, 300, 400
    ]
    once = ProfPolicy(steps=3, every=0, start=7)
    assert [s for s in range(50) if once.window_start(s)] == [7]


def test_parse_step_window():
    assert parse_step_window("3:9") == (3, 9)
    for bad in ("9", "4:4", "5:2", "-1:3"):
        with pytest.raises(ValueError):
            parse_step_window(bad)


def test_profiler_capsule_installs_env_policy(monkeypatch, tmp_path):
    import rocket_tpu as rt

    monkeypatch.setenv("ROCKET_TPU_PROF", "2@50")
    profiler = rt.Profiler(trace_dir=str(tmp_path))
    assert (profiler._trace_start, profiler._trace_steps,
            profiler._trace_every) == (50, 2, 50)
    monkeypatch.setenv("ROCKET_TPU_PROF", "junk")
    with pytest.raises(ValueError):
        rt.Profiler(trace_dir=str(tmp_path))
    # An explicit window wins over the env.
    monkeypatch.setenv("ROCKET_TPU_PROF", "2@50")
    explicit = rt.Profiler(trace_dir=str(tmp_path), trace_start=5,
                           trace_steps=4)
    assert (explicit._trace_start, explicit._trace_steps,
            explicit._trace_every) == (5, 4, 0)
    # trace_every alone is a real periodic request, not a silent no-op:
    # the first window opens at trace_every (ProfPolicy's N@M shape).
    monkeypatch.delenv("ROCKET_TPU_PROF")
    periodic = rt.Profiler(trace_dir=str(tmp_path), trace_steps=2,
                           trace_every=40)
    assert (periodic._trace_start, periodic._trace_steps,
            periodic._trace_every) == (40, 2, 40)
    with pytest.raises(ValueError):
        rt.Profiler(trace_dir=str(tmp_path), trace_steps=5,
                    trace_every=5)


def test_profiler_periodic_windows_reopen(monkeypatch, tmp_path, runtime):
    """The N@M policy must re-trace: window at step M, again at 2M."""
    import jax

    import rocket_tpu as rt

    calls = []
    monkeypatch.setattr(jax.profiler, "start_trace",
                        lambda d, **kw: calls.append("start"))
    monkeypatch.setattr(jax.profiler, "stop_trace",
                        lambda: calls.append("stop"))
    profiler = rt.Profiler(trace_dir=str(tmp_path), trace_start=4,
                           trace_steps=2, trace_every=4, runtime=runtime)
    profiler.setup()
    profiler.set()
    for _ in range(13):
        profiler.launch(None)
    assert calls == ["start", "stop", "start", "stop", "start"]


def test_provision_backend_measure_mode_respects_platform(monkeypatch):
    """The calib subcommand MEASURES: its provisioning must not force
    the CPU default (a real accelerator, when present, is the machine
    to measure) — only the static audits pin cpu."""
    import os as _os

    from rocket_tpu.analysis import backend

    monkeypatch.delenv("JAX_PLATFORMS", raising=False)
    monkeypatch.setenv("XLA_FLAGS", "")
    backend.provision_cpu_backend(force_cpu_default=False)
    assert "JAX_PLATFORMS" not in _os.environ
    assert "xla_force_host_platform_device_count" in _os.environ["XLA_FLAGS"]
    backend.provision_cpu_backend(force_cpu_default=True)
    assert _os.environ["JAX_PLATFORMS"] == "cpu"
    from rocket_tpu.analysis.__main__ import AUDIT_SUBCOMMANDS

    assert AUDIT_SUBCOMMANDS["calib"].measures
    assert not AUDIT_SUBCOMMANDS["sched"].measures


def test_trace_session_writes_capture_sidecar(monkeypatch, tmp_path):
    """stop() records WHICH machine measured (the sidecar); a re-render
    elsewhere reads it instead of claiming its own device kind."""
    import jax

    from rocket_tpu.obs.prof import TraceSession, capture_metadata

    monkeypatch.setattr(jax.profiler, "start_trace", lambda d, **kw: None)
    monkeypatch.setattr(jax.profiler, "stop_trace", lambda: None)
    nested = tmp_path / "plugins" / "profile" / "ts1"
    nested.mkdir(parents=True)
    trace = nested / "perfetto_trace.json.gz"
    with gzip.open(trace, "wt") as f:
        f.write("[]")
    session = TraceSession(str(tmp_path))
    session.start()
    assert session.stop() == str(trace)
    # Found from the trace file (walks up to the capture root) and from
    # the capture dir itself; absent elsewhere.
    for probe in (str(trace), str(tmp_path)):
        meta = capture_metadata(probe)
        assert meta["device_kind"] == jax.devices()[0].device_kind
        assert meta["n_devices"] >= 1
    assert capture_metadata(str(tmp_path / "plugins")) != {}
    assert capture_metadata("/definitely/not/a/capture") == {}


# -- canonicalization / categories ------------------------------------------

def test_canonical_names_and_opcodes():
    assert canonical_op_name("%dot.5") == "dot.5"
    assert canonical_op_name("tanh.4.clone") == "tanh.4"
    assert canonical_op_name("fusion.12.clone.clone") == "fusion.12"
    assert opcode_of("dot.5") == "dot"
    assert opcode_of("all-reduce.17") == "all-reduce"
    assert opcode_of("transpose_copy_fusion.3") == "transpose_copy_fusion"
    assert opcode_of("call") == "call"


def test_collective_set_pinned_to_shard_audit():
    """obs stays import-light by duplicating the collective-kind set —
    this pin keeps the copies equal."""
    from rocket_tpu.analysis.shard_audit import COLLECTIVE_KINDS

    assert frozenset(COLLECTIVE_KINDS) == COLLECTIVE_OPS


def test_categorize_by_opcode_and_hlo_category():
    assert categorize("all-reduce") == "collective"
    assert categorize("dot") == "compute"
    assert categorize("copy") == "memory"
    assert categorize("tanh") == "other"
    # TPU traces carry hlo_category per op — it wins over the opcode.
    assert categorize("anything", "all-reduce") == "collective"
    assert categorize("anything", "loop fusion") == "compute"
    assert categorize("anything", "data formatting") == "memory"


# -- synthetic-event parsing -------------------------------------------------

def _dev(name, ts, dur, module="jit_step", category=None, tid=2):
    args = {"hlo_op": name, "hlo_module": module}
    if category is not None:
        args["hlo_category"] = category
    return {"ph": "X", "pid": 1, "tid": tid, "ts": ts, "dur": dur,
            "name": name, "args": args}


def _step(step, ts, dur, name="train"):
    return {"ph": "X", "pid": 1, "tid": 1, "ts": ts, "dur": dur,
            "name": name, "args": {"step_num": str(step)}}


def test_parse_buckets_slices_by_step_and_name():
    events = [
        _step(0, 0, 100),
        _step(1, 200, 100),
        _dev("dot.1", 10, 40),            # step 0
        _dev("dot.1", 210, 50),           # step 1
        _dev("all-reduce.2", 260, 20),    # step 1
        _dev("dot.1", 500, 30),           # outside every window
    ]
    summary = parse_trace(events)
    assert len(summary.steps) == 2
    assert summary.n_slices == 4
    assert summary.unattributed_us == 30
    dot = next(op for op in summary.ops if op.name == "dot.1")
    assert (dot.count, dot.total_us) == (3, 120)
    assert summary.steps[0].categories == {"compute": 40}
    assert summary.steps[1].categories == {"compute": 50, "collective": 20}
    # Step spans: device activity inside the window.
    assert summary.steps[0].device_span_us == 40
    assert summary.steps[1].device_span_us == 70
    # Duplicate step annotations (other threads) merge, and step_name
    # filters foreign annotations out.
    summary2 = parse_trace(
        events + [_step(1, 150, 200), _step(7, 0, 1000, name="eval")],
        step_name="train",
    )
    assert len(summary2.steps) == 2
    assert summary2.steps[1].start_us == 150


def test_measured_exposed_comm_interval_math():
    events = [
        _step(0, 0, 1000),
        _dev("dot.1", 0, 100),                 # compute covers [0, 100)
        _dev("all-reduce.1", 50, 100),         # [50,150): 50 exposed
        _dev("all-reduce.2", 400, 50),         # fully exposed
        _dev("all-reduce.3", 90, 20),          # nested in compute + ar1
    ]
    summary = parse_trace(events)
    rec = summary.steps[0]
    # Collective union [50,150)+[400,450) = 150us; compute cover [0,100)
    # overlaps 50 of it -> exposed 100.
    assert rec.exposed_comm_us == pytest.approx(100.0)
    assert rec.device_busy_us == pytest.approx(100 + 50 + 50)
    assert rec.device_span_us == pytest.approx(450.0)


def test_prof_record_and_publish_gauges():
    events = [
        _step(0, 0, 200), _dev("dot.1", 10, 100),
        _dev("all-reduce.1", 120, 40),
    ]
    summary = parse_trace(events)
    record = prof_record(summary)
    assert record["n_steps"] == 1
    assert record["measured_step_us"] == pytest.approx(150.0)
    assert record["exposed_comm_us"] == pytest.approx(40.0)
    assert record["category_fractions"]["compute"] == pytest.approx(
        100 / 140, abs=1e-4
    )
    registry = MetricsRegistry()
    publish_prof(registry, record)
    scalars = registry.scalars()
    assert scalars["obs/prof/measured_step_us"] == pytest.approx(150.0)
    assert scalars["obs/prof/frac_collective"] == pytest.approx(
        40 / 140, abs=1e-4
    )
    assert scalars["obs/prof/windows_parsed"] == 1.0
    assert "dot.1" in render_prof(summary, record)


# -- the checked-in CPU capture ---------------------------------------------

def test_fixture_trace_parses_with_steps_and_hlo_ops():
    assert find_trace_file(FIXTURE_TRACE) == FIXTURE_TRACE
    assert find_trace_file(os.path.dirname(FIXTURE_TRACE)) == FIXTURE_TRACE
    summary = parse_trace(load_trace_events(FIXTURE_TRACE),
                          step_name="train")
    assert len(summary.steps) == 3
    assert summary.modules.get("jit_step", 0) > 0
    names = {op.name for op in summary.ops}
    assert {"dot.3", "dot.5"} <= names
    # The backend's .clone thunk suffix canonicalizes away.
    assert "tanh.4" in names and "tanh.4.clone" not in names
    assert all(s.device_span_us > 0 for s in summary.steps)


def test_load_trace_events_rejects_garbage(tmp_path):
    bad = tmp_path / "x.json"
    bad.write_text("{\"notTraceEvents\": 3}")
    with pytest.raises(ValueError):
        load_trace_events(str(bad))
    gz = tmp_path / "y.json.gz"
    with gzip.open(gz, "wt") as f:
        f.write("not json")
    with pytest.raises(ValueError):
        load_trace_events(str(gz))
    assert find_trace_file(str(tmp_path / "nothing")) is None


# -- histogram quantile estimation ------------------------------------------

def test_estimate_quantiles_from_pow2_buckets():
    from rocket_tpu.obs.registry import Histogram

    hist = Histogram(base=1e-6)
    for value in [1e-6] * 50 + [3e-6] * 40 + [100e-6] * 10:
        hist.observe(value)
    snap = hist.snapshot()
    q = estimate_quantiles(snap)
    assert set(q) == {"p50", "p90", "p99"}
    # p50 in the first bucket (<=1us), p90 near the 2-4us bucket, p99 in
    # the tail bucket; clamped to observed extremes.
    assert q["p50"] <= 2e-6
    assert 2e-6 <= q["p90"] <= 8e-6
    assert 8e-6 <= q["p99"] <= 128e-6
    assert q["p50"] <= q["p90"] <= q["p99"]
    assert estimate_quantiles({"count": 0, "buckets": {}}) == {}
    assert estimate_quantiles({}) == {}
    assert estimate_quantiles({"count": 3, "buckets": "junk"}) == {}


# -- reconcile against a fake priced DAG ------------------------------------

def _fake_priced():
    from rocket_tpu.analysis.sched_audit import OpCost

    def op(name, kind, time_us, where="", opcode=None):
        return OpCost(
            name=name, opcode=opcode or name.split(".")[0], kind=kind,
            time_s=time_us * 1e-6, flops=0.0, hbm_bytes=0, comm_bytes=0,
            is_comm=kind == "comm", operands=(), where=where,
        )

    return [
        op("dot.1", "compute", 50.0, where="matmul layers.py:10"),
        op("all-reduce.1", "comm", 5.0, where="psum grad.py:20"),
        op("copy.7", "memory", 2.0),
        op("fusion.9", "memory", 1.0),              # priced, unmeasured
        op("tuple.1", "free", 0.0),                 # never joins
        op("all-reduce.1-done", "comm", 0.0, opcode="all-reduce-done"),
    ]


def _fake_summary():
    events = [
        _step(0, 0, 1000), _step(1, 1000, 1000),
        # dot.1: two steps, 100us each; measured category deliberately
        # WRONG ("other" would be the parser's guess for a weird name)
        # to prove the priced kind wins after the join.
        _dev("dot.1", 10, 100), _dev("dot.1", 1010, 100),
        _dev("all-reduce.1", 120, 40), _dev("all-reduce.1", 1120, 40),
        _dev("mystery.3", 300, 10), _dev("mystery.3", 1300, 10),
    ]
    return parse_trace(events)


def test_reconcile_joins_by_name_with_signed_errors():
    from rocket_tpu.analysis.calib import reconcile

    record, rows = reconcile(
        _fake_summary(), _fake_priced(),
        {"predicted_step_time_us": 70.0, "exposed_comm_us": 5.0,
         "device_kind": "TPU v5 lite", "flops_per_step": 0.0,
         "predicted_mfu": 0.1},
        label="fake",
    )
    assert record["n_joined_ops"] == 2
    joined = {r["name"]: r for r in rows}
    # Per-execution comparand: 100us per dot execution vs 50 predicted.
    assert joined["dot.1"]["measured_us"] == pytest.approx(100.0)
    assert joined["dot.1"]["error"] == pytest.approx(-0.5)
    assert joined["dot.1"]["category"] == "compute"  # priced kind wins
    assert joined["dot.1"]["where"] == "matmul layers.py:10"
    assert joined["all-reduce.1"]["category"] == "collective"
    # Coverage is time-weighted: (200 + 80) of (200 + 80 + 20).
    assert record["join_coverage"] == pytest.approx(280 / 300, abs=1e-4)
    assert record["unjoined_fraction"] == pytest.approx(20 / 300, abs=1e-4)
    # Headline: measured span (first-to-last device activity, 300us per
    # step: [10, 310)) vs predicted 70.
    assert record["measured_step_us"] == pytest.approx(300.0)
    assert record["calib_error"] == pytest.approx(
        (70 - 300) / 300, abs=1e-3
    )
    assert record["abs_calib_error"] == pytest.approx(
        abs(record["calib_error"])
    )
    # Per-category: predicted totals cover ALL priced ops (fusion.9's
    # memory us rides in), measured totals all measured ops.
    assert record["categories"]["memory"]["predicted_us"] == pytest.approx(
        3.0
    )
    assert record["categories"]["collective"]["measured_us"] == \
        pytest.approx(40.0)
    assert record["measured_exposed_comm_us"] == pytest.approx(40.0)


def test_reconcile_picks_best_module():
    from rocket_tpu.analysis.calib import reconcile

    events = [
        _step(0, 0, 1000),
        _dev("dot.1", 10, 100, module="jit_other"),
        _dev("dot.1", 200, 30, module="jit_right"),
        _dev("all-reduce.1", 300, 10, module="jit_right"),
    ]
    summary = parse_trace(events)
    # jit_other holds more dot.1 time, but jit_right covers MORE priced
    # time... both join dot.1; the picker is time-weighted, so jit_other
    # (100us joined) wins over jit_right (40us) — pin the explicit
    # module override instead, the auditor's path.
    record, rows = reconcile(
        summary, _fake_priced(),
        {"predicted_step_time_us": 70.0, "device_kind": "TPU v5 lite"},
        module="jit_right", label="fake",
    )
    assert record["module"] == "jit_right"
    assert {r["name"] for r in rows} == {"dot.1", "all-reduce.1"}


def test_zero_step_capture_fails_the_gate_not_silently(monkeypatch,
                                                       tmp_path):
    """A capture with no annotated step windows yields a None headline
    error, which the budget diff would silently skip — the target must
    FAIL with RKT702 instead of gating nothing."""
    from rocket_tpu.analysis import calib

    monkeypatch.setattr(
        calib, "priced_ops_for_target",
        lambda t: ("fake-compiled", [], {"module": "jit_x"}, None, []),
    )
    monkeypatch.setattr(
        calib, "capture_target_trace",
        lambda t, c, a, d: str(tmp_path / "t.json"),
    )
    monkeypatch.setattr(calib, "load_trace_events", lambda p: [])
    report = calib._run_train_target(
        calib.CALIB_TARGETS["gpt2_sentinel"], str(tmp_path)
    )
    assert report.record == {}
    assert [f.rule for f in report.findings] == ["RKT702"]
    assert "StepTraceAnnotation" in report.findings[0].message


def test_serve_cli_rejects_malformed_trace_window_at_parse_time():
    """--trace-steps must fail at argparse (exit 2), before the model
    builds."""
    from rocket_tpu.serve.__main__ import main as serve_main

    for bad in ("7", "8:3", "x:y"):
        with pytest.raises(SystemExit) as exc:
            serve_main(["--requests", "1", "--trace-steps", bad])
        assert exc.value.code == 2


def test_render_calib_survives_nullable_fields():
    """The record schema allows nulls (no annotated steps, a category
    with zero measured time, unknown measured peak) — the render must
    never crash on its own record."""
    from rocket_tpu.analysis.calib import render_calib

    out = render_calib({
        "target": "t", "kind": "train", "n_steps": 0,
        "measured_step_us": 0.0, "predicted_step_us": 10.0,
        "calib_error": None, "join_coverage": 0.0,
        "measured_exposed_comm_us": 0.0,
        "predicted_exposed_comm_us": 1.0,
        "measured_mfu": None, "predicted_mfu": None,
        "categories": {"other": {"measured_us": 5.0, "predicted_us": 0.0,
                                 "error": None}},
        "top_offenders": [],
    })
    assert "calibration [t]" in out and "None" in out
    out = render_calib({"kind": "serve", "target": "s",
                        "measured_itl_us": None,
                        "predicted_itl_us": 1.0, "decode_waves": 0,
                        "calib_error": None})
    assert "serve calibration [s]" in out


def test_calib_rule_checks():
    assert check_join_coverage(0.9, 0.5) == []
    assert check_join_coverage(0.2, 0.0) == []       # disabled
    findings = check_join_coverage(0.2, 0.5, label="t")
    assert len(findings) == 1 and findings[0].rule == "RKT702"
    # Ceiling: only bites on matched hardware.
    assert check_error_ceiling(-5.0, 3.0, device_matched=False) == []
    assert check_error_ceiling(-2.0, 3.0, device_matched=True) == []
    assert check_error_ceiling(None, 3.0, device_matched=True) == []
    assert check_error_ceiling(-5.0, None, device_matched=True) == []
    findings = check_error_ceiling(-5.0, 3.0, device_matched=True,
                                   label="t")
    assert len(findings) == 1 and findings[0].rule == "RKT703"


def test_drifted_budget_fixture_trips_rkt701():
    """The seeded-bad fixture (a budget claiming far tighter calibration
    than this container can produce) must make the shared diff loop
    fire RKT701 — the true-positive CI leg's in-process half."""
    from rocket_tpu.analysis import budgets as budgets_mod

    committed = budgets_mod.load_budget(DRIFTED_BUDGETS, "gpt2_sentinel")
    real = budgets_mod.load_budget(CALIB_BUDGETS, "gpt2_sentinel")
    assert committed is not None and real is not None
    findings = budgets_mod.diff_budget(
        "gpt2_sentinel", committed, real,
        keys=budgets_mod.CALIB_GATED_KEYS, rule="RKT701", family="calib",
    )
    assert findings and all(f.rule == "RKT701" for f in findings)
    assert any("abs_calib_error" in f.message for f in findings)
    # And the real committed budget against itself is clean.
    assert budgets_mod.diff_budget(
        "gpt2_sentinel", real, real,
        keys=budgets_mod.CALIB_GATED_KEYS, rule="RKT701", family="calib",
    ) == []


def test_calib_budgets_and_targets_stay_bijective():
    from rocket_tpu.analysis.calib import CALIB_TARGETS

    committed = {
        os.path.splitext(f)[0]
        for f in os.listdir(CALIB_BUDGETS) if f.endswith(".json")
    }
    assert committed == {
        name for name, t in CALIB_TARGETS.items() if not t.demo
    }
    drifted = {
        os.path.splitext(f)[0]
        for f in os.listdir(DRIFTED_BUDGETS) if f.endswith(".json")
    }
    assert drifted == committed


# -- serve engine capture window --------------------------------------------

def test_serve_capture_trace_validates_window(tmp_path):
    import jax

    from rocket_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
    )
    from rocket_tpu.serve.api import ServeConfig, ServeEngine

    model = TransformerLM(TransformerConfig(
        vocab_size=64, max_seq_len=64, dim=32, num_layers=1, num_heads=2,
        dropout=0.0,
    ))
    params = jax.jit(model.init)(jax.random.key(0))["params"]
    engine = ServeEngine(model, params, ServeConfig(
        max_slots=2, block_len=16, prefill_chunk=16,
    ))
    for bad in ("junk", "5:5", (3, 2)):
        with pytest.raises(ValueError):
            engine.capture_trace(bad, str(tmp_path))
    # Arming without stepping never opens a session; finish_trace is a
    # safe no-op.
    engine.capture_trace("0:2", str(tmp_path / "tr"))
    assert engine.finish_trace() is None


def test_report_renders_prof_section_and_quantile_rows(tmp_path):
    """`obs report` on a telemetry.json with obs/prof gauges and a
    histogram renders the measured-attribution section and estimated
    p50/p90/p99 rows."""
    from rocket_tpu.obs.__main__ import _render_prof_gauges, _report_telemetry

    metrics = {
        "counters": {"obs/prof/windows_parsed": 2.0},
        "gauges": {
            "obs/prof/n_steps": 3.0,
            "obs/prof/measured_step_us": 1234.5,
            "obs/prof/device_busy_us": 1000.0,
            "obs/prof/wall_step_us": 1300.0,
            "obs/prof/exposed_comm_us": 12.0,
            "obs/prof/frac_compute": 0.7,
            "obs/prof/frac_collective": 0.1,
        },
        "histograms": {
            "data/wait_s": {
                "count": 10, "total": 0.01, "mean": 0.001,
                "min": 0.0005, "max": 0.004,
                "buckets": {"le_0.001": 6, "le_0.002": 3, "le_0.004": 1},
            },
        },
    }
    section = _render_prof_gauges(metrics)
    assert "measured step attribution" in section
    assert "compute=70.0%" in section
    assert _render_prof_gauges({"gauges": {}}) == ""
    doc = {"goodput": {"total_wall_s": 1.0,
                       "categories": {"step": 1.0}, "fractions": {}},
           "metrics": metrics}
    out = _report_telemetry(doc)
    assert "p50=" in out and "p99=" in out
    assert "measured step attribution" in out


# -- CLI contracts -----------------------------------------------------------

def run_obs(*args, timeout=300):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", *args],
        capture_output=True, text=True, cwd=REPO, env=env,
        timeout=timeout,
    )


def test_obs_prof_cli_renders_fixture():
    proc = run_obs("prof", FIXTURE_TRACE, "--step-name", "train")
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "device trace" in proc.stdout
    assert "dot.3" in proc.stdout          # nonempty attribution table
    assert "3 annotated step(s)" in proc.stdout


def test_obs_prof_cli_json_shape():
    proc = run_obs("prof", FIXTURE_TRACE, "--format", "json")
    assert proc.returncode == 0, proc.stderr
    record = json.loads(proc.stdout)
    for key in ("n_steps", "measured_step_us", "categories_us",
                "top_ops", "trace_file"):
        assert key in record
    assert record["n_steps"] == 3


def test_obs_prof_cli_exit_two_on_garbage(tmp_path):
    assert run_obs("prof", str(tmp_path / "missing")).returncode == 2
    bad = tmp_path / "bad.json"
    bad.write_text("[]")  # valid JSON, but no device slices
    assert run_obs("prof", str(bad)).returncode == 2
    proc = run_obs("prof", FIXTURE_TRACE, "--target", "not_a_target")
    assert proc.returncode == 2


@pytest.mark.slow
def test_calib_cli_capture_parse_reconcile_e2e(tmp_path):
    """The acceptance path: `analysis calib` on the gpt2 sentinel —
    capture a CPU trace of the compiled step, parse it, reconcile
    against the priced DAG, hold the committed budget. Then the drifted
    seeded-bad budget must fail with RKT701, and `obs prof --target`
    must render the join from the kept trace."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.analysis", "calib",
         "--target", "gpt2_sentinel", "--budgets",
         os.path.join("tests", "fixtures", "budgets", "calib")],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    drifted = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.analysis", "calib",
         "--target", "gpt2_sentinel", "--budgets",
         os.path.join("tests", "fixtures", "budgets", "calib_drifted"),
         "--format", "json"],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=600,
    )
    assert drifted.returncode == 1, drifted.stdout + drifted.stderr
    rules = {f["rule"] for f in json.loads(drifted.stdout)}
    assert rules == {"RKT701"}
    proc = run_obs(
        "prof", os.path.join("runs", "prof", "gpt2_sentinel"),
        "--target", "gpt2_sentinel", timeout=600,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "calibration [gpt2_sentinel]" in proc.stdout
    assert "top measured-vs-predicted offenders" in proc.stdout
