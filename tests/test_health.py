"""rocket_tpu.obs.health + obs.flight: in-step health sentinels, the
anomaly policy (warn / skip_step / dump_and_halt), the lagged host fetch,
and the black-box flight recorder with forensic bundles."""

import glob
import json
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.obs import (
    HealthAnomalyError,
    Telemetry,
    Watchdog,
    flight as flight_lib,
    health as health_lib,
)
from rocket_tpu.obs.registry import MetricsRegistry
from rocket_tpu.runtime.context import Runtime


# -- device half: word compute / decode -------------------------------------


def test_health_word_roundtrip_clean_and_nan():
    params = {"dense": {"w": jnp.ones((4, 4))}, "head": {"w": jnp.ones((4,))}}
    branches = health_lib.branch_names(params)
    assert branches == ("dense", "head")

    def one_step(loss, grads, new_params):
        h = health_lib.init_state()
        step_ok, loss_ok, g_ok, grad_norm = health_lib.step_flags(loss, grads)
        h2, word, extras = health_lib.update_sentinels(
            h, loss=loss, step=jnp.zeros((), jnp.int32), step_ok=step_ok,
            loss_ok=loss_ok, grad_branch_ok=g_ok, grad_norm=grad_norm,
            update_norm=jnp.zeros((), jnp.float32), new_params=new_params,
            gated=True, ema_decay=0.98, zscore_max=8.0, zscore_warmup=20,
        )
        return word

    clean = jax.jit(one_step)(jnp.float32(1.5), params, params)
    rec = health_lib.decode_word(np.asarray(clean), branches)
    assert rec["flags"] == 0 and rec["flag_names"] == []
    assert rec["loss"] == pytest.approx(1.5)
    assert rec["skipped_total"] == 0 and rec["anomalies_total"] == 0
    assert rec["update_ratio"] == pytest.approx(0.0)

    bad_grads = {"dense": {"w": jnp.full((4, 4), jnp.nan)},
                 "head": {"w": jnp.ones((4,))}}
    bad = jax.jit(one_step)(jnp.float32(jnp.nan), bad_grads, params)
    rec = health_lib.decode_word(np.asarray(bad), branches)
    assert set(rec["flag_names"]) == {"loss_nonfinite", "grads_nonfinite"}
    assert rec["bad_grad_branches"] == ["dense"]
    assert rec["bad_param_branches"] == []
    assert rec["skipped_total"] == 1  # gated=True counts the skip on device
    assert rec["anomalies_total"] == 1


def test_nan_loss_does_not_poison_the_ema():
    h = health_lib.init_state()
    kwargs = dict(
        grad_branch_ok=jnp.ones((1,)), grad_norm=jnp.float32(1.0),
        update_norm=jnp.float32(0.0), new_params={"w": jnp.ones(2)},
        gated=False, ema_decay=0.9, zscore_max=8.0, zscore_warmup=2,
    )
    for step, loss in enumerate([1.0, 1.0, float("nan"), 1.0]):
        loss = jnp.float32(loss)
        ok = jnp.isfinite(loss)
        h, word, _ = health_lib.update_sentinels(
            h, loss=loss, step=jnp.int32(step),
            step_ok=ok, loss_ok=ok, **kwargs,
        )
    assert float(h["loss_ema"]) == pytest.approx(1.0)
    assert int(h["count"]) == 3  # the NaN step did not advance the EMA


# -- host half: monitor lag + policy ----------------------------------------


def _word(step, flags=0.0, n_branches=1, skipped=0, anomalies=0):
    word = np.zeros(health_lib.word_length(n_branches), np.float32)
    word[health_lib.SLOT_STEP] = step
    word[health_lib.SLOT_FLAGS] = flags
    word[health_lib.SLOT_SKIPPED] = skipped
    word[health_lib.SLOT_ANOMALIES] = anomalies
    return word


def test_monitor_fetches_lagged_and_counts_anomalies():
    reg = MetricsRegistry()
    mon = health_lib.HealthMonitor(
        health_lib.HealthConfig(enabled=True, action="warn", fetch_lag=2),
        registry=reg,
    )
    mon.register_step("train_step[MLP]", ("params",))
    mon.observe("train_step[MLP]", 0, _word(0))
    mon.observe("train_step[MLP]", 1, _word(1))
    assert mon.last_good_step is None  # both still inside the fetch lag
    mon.observe(
        "train_step[MLP]", 2,
        _word(2, flags=health_lib.FLAG_LOSS_NONFINITE, anomalies=1),
    )
    assert mon.last_good_step == 0  # word 0 just crossed the lag
    mon.drain()
    assert mon.last_good_step == 1  # step 2 is anomalous, 1 is the last good
    assert mon.summary()["anomalies"] == 1
    assert reg.snapshot()["gauges"]["health/last_good_step"] == 1.0


def test_monitor_dump_and_halt_raises_once():
    mon = health_lib.HealthMonitor(
        health_lib.HealthConfig(enabled=True, action="dump_and_halt",
                                fetch_lag=1),
    )
    mon.observe("s", 0, _word(0))
    mon.observe(
        "s", 1, _word(1, flags=health_lib.FLAG_GRADS_NONFINITE, anomalies=1),
    )  # word 1 is still inside the fetch lag here
    with pytest.raises(HealthAnomalyError):
        # Observing word 2 fetches the lagged anomalous word 1.
        mon.observe(
            "s", 2, _word(2, flags=health_lib.FLAG_GRADS_NONFINITE,
                          anomalies=2),
        )
    # A second anomalous word after the halt is noise, not a second raise.
    mon.drain()


def test_register_step_disambiguates_conflicting_layouts():
    """Two Modules wrapping the same model class must not decode each
    other's words: a conflicting layout under an existing label gets a
    #N suffix (and its own lag queue); identical re-registration is
    idempotent."""
    mon = health_lib.HealthMonitor(
        health_lib.HealthConfig(enabled=True, fetch_lag=2)
    )
    first = mon.register_step("train_step[MLP]", ("enc", "head"))
    again = mon.register_step("train_step[MLP]", ("enc", "head"))
    other = mon.register_step("train_step[MLP]", ("torso", "policy"))
    assert first == again == "train_step[MLP]"
    assert other == "train_step[MLP]#2"
    # Distinct labels keep their full fetch lag: two interleaved streams,
    # neither fetches until ITS OWN queue exceeds the lag.
    mon.observe(first, 0, _word(0))
    mon.observe(other, 0, _word(0))
    mon.observe(first, 1, _word(1))
    mon.observe(other, 1, _word(1))
    assert mon.last_good_step is None
    mon.observe(first, 2, _word(2))
    assert mon.last_good_step == 0


def test_disabled_monitor_is_inert():
    mon = health_lib.HealthMonitor(health_lib.HealthConfig(enabled=False))
    mon.observe("s", 0, object())  # never touched, never fetched
    mon.drain()
    mon.note_nonfinite_metric("acc")
    assert mon.summary()["enabled"] is False


def test_invalid_anomaly_action_rejected(tmp_path):
    with pytest.raises(ValueError, match="anomaly_action"):
        Runtime(seed=0, project_dir=str(tmp_path), health=True,
                anomaly_action="explode")


def test_env_var_enables_health_with_action(tmp_path, monkeypatch):
    monkeypatch.setenv("ROCKET_TPU_HEALTH", "skip_step")
    runtime = Runtime(seed=0, project_dir=str(tmp_path))
    try:
        assert runtime.health.enabled
        assert runtime.health.config.action == "skip_step"
        assert runtime.telemetry.enabled  # health implies telemetry
        assert runtime.flight is not None
    finally:
        runtime.end_training()


def test_telemetry_json_stays_strict_json_with_nan_gauges(tmp_path):
    """An anomaly legitimately leaves NaN in the health gauges;
    telemetry.json must still be RFC-valid JSON (string-encoded), not a
    bare NaN token that jq / JSON.parse reject."""
    tel = Telemetry(enabled=True, out_dir=str(tmp_path))
    tel.registry.gauge("health/loss").set(float("nan"))
    tel.registry.gauge("health/grad_norm").set(float("inf"))
    out = tel.flush()
    raw = open(os.path.join(out, "telemetry.json")).read()

    def no_bare_constants(name):
        raise AssertionError(f"bare {name} token in telemetry.json")

    doc = json.loads(raw, parse_constant=no_bare_constants)
    assert doc["metrics"]["gauges"]["health/loss"] == "NaN"
    assert doc["metrics"]["gauges"]["health/grad_norm"] == "Infinity"


# -- flight recorder --------------------------------------------------------


def test_flight_ring_is_bounded_and_tracks_last_good():
    rec = flight_lib.FlightRecorder(max_steps=3)
    for step in range(6):
        rec.record({"step": step, "flag_names": []})
    rec.record({"step": 6, "flag_names": ["loss_nonfinite"]})
    assert len(rec) == 3
    assert rec.last_good_step == 5


def test_flight_dump_writes_manifest_and_respects_budget(tmp_path):
    tel = Telemetry(enabled=True, out_dir=str(tmp_path))
    rec = flight_lib.FlightRecorder(max_steps=8, telemetry=tel, max_dumps=2)
    rec.record({"step": 0, "flag_names": []})
    rec.note_anomaly({"step": 1, "flag_names": ["loss_nonfinite"]})
    first = rec.dump("anomaly_step1", extra={"note": "test"})
    again = rec.dump("anomaly_step1")  # same reason -> deduped directory
    assert first != again and os.path.isdir(first) and os.path.isdir(again)
    assert rec.dump("third") is None  # budget of 2 spent
    manifest = json.load(open(os.path.join(first, "blackbox.json")))
    assert manifest["reason"] == "anomaly_step1"
    assert manifest["last_good_step"] == 0
    assert manifest["anomalies"][0]["step"] == 1
    assert manifest["extra"]["note"] == "test"
    assert manifest["checkpoint"] is None  # no Checkpointer attached


def test_flight_dump_gated_to_main_process(tmp_path):
    """Only the main process writes bundles — the same gate the (slow)
    two-process test asserts end-to-end via per-rank project dirs."""

    class FakeRuntime:
        project_dir = None
        is_main_process = False
        process_index = 1
        process_count = 2

        def rng_state_dict(self):
            return {"seed": 0, "key_counter": 0}

    fake = FakeRuntime()
    fake.project_dir = str(tmp_path)
    rec = flight_lib.FlightRecorder(max_steps=4, runtime=fake)
    rec.record({"step": 0, "flag_names": []})
    assert rec.dump("anomaly") is None
    assert not os.path.isdir(tmp_path / "runs" / "telemetry" / "blackbox")
    fake.is_main_process = True
    bundle = rec.dump("anomaly")
    assert bundle is not None and os.path.isdir(bundle)


# -- watchdog escalation ----------------------------------------------------


def test_watchdog_escalates_after_consecutive_stalls():
    escalations = []
    dog = Watchdog(0.08, poll_s=0.02, escalate_after=2,
                   on_escalate=escalations.append)
    dog.start()
    try:
        dog.arm()
        deadline = time.time() + 5.0
        while not escalations and time.time() < deadline:
            time.sleep(0.02)
    finally:
        dog.stop()
    assert len(escalations) == 1  # fired exactly once per wedge
    assert dog.stall_count >= 2
    assert dog.escalation_count == 1


def test_watchdog_beat_resets_escalation():
    escalations = []
    dog = Watchdog(0.1, poll_s=0.02, escalate_after=3,
                   on_escalate=escalations.append)
    dog.start()
    try:
        dog.arm()
        for _ in range(8):  # two stall windows' worth, beating in between
            time.sleep(0.06)
            dog.beat()
    finally:
        dog.stop()
    assert escalations == []


# -- end-to-end -------------------------------------------------------------


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def _poisoned_data(n=128, nan_from=64, nan_to=72):
    """One all-NaN batch (batch_size=32 -> batch index 2)."""
    rng = np.random.default_rng(0)
    data = []
    for i in range(n):
        image = rng.normal(size=8).astype(np.float32)
        if nan_from <= i < nan_to:
            image[:] = np.nan
        data.append({"image": image, "label": np.int32(i % 4)})
    return data


class GrabParams(rt.Capsule):
    """Holds the latest params reference so finiteness is checkable after
    DESTROY tears the module down."""

    def __init__(self, module):
        super().__init__(priority=10)
        self._module = module
        self.params = None

    def launch(self, attrs=None):
        if self._module.state is not None:
            self.params = self._module.state["params"]


def _tree(runtime, tmp_path, module_kwargs=None, extra=(),
          num_epochs=2, data=None):
    module = rt.Module(
        MLP(in_features=8, num_classes=4, hidden=(16,)),
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
        **(module_kwargs or {}),
    )
    grab = GrabParams(module)
    launcher = rt.Launcher(
        [rt.Looper(
            [rt.Dataset(data if data is not None else _poisoned_data(),
                        batch_size=32), module, grab,
             *extra],
            tag="train", progress=False,
        )],
        num_epochs=num_epochs, runtime=runtime,
    )
    return launcher, module, grab


def test_skip_step_survives_nan_batch_with_finite_params(tmp_path):
    """Acceptance: an injected-NaN batch under skip_step finishes the run
    with finite params and a counted skip — under strict mode, proving
    the sentinel path adds no implicit transfer."""
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        strict=True, health=True, anomaly_action="skip_step",
    )
    launcher, module, grab = _tree(runtime, tmp_path)
    launcher.launch()

    summary = runtime.health.summary()
    assert summary["anomalies"] == 2       # one poisoned batch per epoch
    assert summary["skipped_steps"] == 2
    host = jax.device_get(grab.params)
    assert all(np.isfinite(leaf).all() for leaf in jax.tree.leaves(host))
    # The registry carries the decoded sentinels for the dashboard.
    gauges = runtime.telemetry.registry.snapshot()["gauges"]
    assert gauges["health/skipped_steps"] == 2.0
    assert gauges["health/anomalies"] == 2.0


def test_skip_step_gates_accumulation_window(tmp_path):
    """With gradient accumulation, the poisoned microbatch drops out of
    the accumulator — the boundary update still applies and params stay
    finite."""
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        gradient_accumulation_steps=2, health=True,
        anomaly_action="skip_step",
    )
    launcher, module, grab = _tree(runtime, tmp_path, num_epochs=1)
    launcher.launch()
    assert runtime.health.summary()["skipped_steps"] == 1
    host = jax.device_get(grab.params)
    assert all(np.isfinite(leaf).all() for leaf in jax.tree.leaves(host))


def test_warn_action_does_not_gate(tmp_path):
    """warn: the anomaly is counted but the update applies — params go
    non-finite (exactly why skip_step exists)."""
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        health=True, anomaly_action="warn",
    )
    launcher, module, grab = _tree(runtime, tmp_path, num_epochs=1)
    launcher.launch()
    summary = runtime.health.summary()
    assert summary["anomalies"] >= 1
    assert summary["skipped_steps"] == 0
    host = jax.device_get(grab.params)
    assert not all(np.isfinite(leaf).all() for leaf in jax.tree.leaves(host))


def test_dump_and_halt_writes_renderable_bundle(tmp_path):
    """Acceptance: dump_and_halt produces a complete blackbox bundle that
    the post-mortem CLI renders (last-good step + anomaly timeline), with
    the emergency checkpoint riding along."""
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        strict=True, health=True, anomaly_action="dump_and_halt",
    )
    launcher, module, grab = _tree(
        runtime, tmp_path,
        extra=(rt.Checkpointer(output_dir=str(tmp_path / "ckpt"),
                               save_every=10_000),),
    )
    with pytest.raises(HealthAnomalyError) as excinfo:
        launcher.launch()
    bundle = excinfo.value.bundle
    assert bundle is not None and os.path.isdir(bundle)
    assert glob.glob(
        str(tmp_path / "runs" / "telemetry" / "blackbox" / "*")
    ) == [bundle]

    manifest = json.load(open(os.path.join(bundle, "blackbox.json")))
    assert manifest["reason"].startswith("anomaly_step")
    assert manifest["last_good_step"] == 1  # poisoned batch is step 2
    assert [rec["step"] for rec in manifest["anomalies"]] == [2]
    assert manifest["anomalies"][0]["flag_names"] == [
        "loss_nonfinite", "grads_nonfinite"
    ]
    assert manifest["sentinel_history"]
    assert manifest["spans_tail"]
    assert manifest["rng"]["seed"] == 0
    # Emergency checkpoint: complete and (single-host) resumable.
    ckpt_index = os.path.join(bundle, "checkpoint", "model_0", "index.json")
    assert os.path.exists(ckpt_index)
    index = json.load(open(ckpt_index))
    assert any(name == "step" for name in index)
    # The gated update kept the dumped state finite.
    from rocket_tpu.runtime import checkpoint_io

    flat = checkpoint_io.load_pytree(os.path.dirname(ckpt_index))
    for name, value in flat.items():
        if name.startswith("params/"):
            assert np.isfinite(value).all(), name

    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "blackbox", bundle],
        capture_output=True, text=True,
    )
    assert proc.returncode == 0, proc.stderr
    assert "last good step: 1" in proc.stdout
    assert "anomaly timeline" in proc.stdout
    assert "loss_nonfinite+grads_nonfinite" in proc.stdout
    assert "emergency checkpoint" in proc.stdout

    # telemetry.json (written by end_training in the Launcher's finally)
    # records the health summary and the bundle path.
    record = json.load(
        open(tmp_path / "runs" / "telemetry" / "telemetry.json")
    )
    assert record["health"]["anomalies"] == 1
    assert record["blackbox"]["bundles"] == [bundle]


def test_watchdog_escalation_dumps_flight_recorder(tmp_path):
    """Acceptance: a genuinely wedged step (consecutive stall windows, no
    beat) escalates from stack dumps to a full black-box bundle carrying
    the watchdog report."""
    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        health=True, watchdog_secs=0.15,
    )
    runtime.telemetry.watchdog._poll_s = 0.02  # fast test cadence

    class Stall(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)
            self.done = False

        def launch(self, attrs=None):
            if not self.done:
                self.done = True
                dog = self._runtime.telemetry.watchdog
                deadline = time.time() + 10.0
                while dog.escalation_count == 0 and time.time() < deadline:
                    time.sleep(0.02)

    data = [{"x": np.float32(i)} for i in range(16)]
    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=8, fuse_gather=False),
                    Stall()], tag="train", progress=False)],
        num_epochs=1, runtime=runtime,
    ).launch()
    bundles = glob.glob(
        str(tmp_path / "runs" / "telemetry" / "blackbox" / "*")
    )
    assert len(bundles) == 1 and "watchdog_stall" in bundles[0]
    manifest = json.load(open(os.path.join(bundles[0], "blackbox.json")))
    assert "no step completed" in manifest["extra"]["report"]


def test_loop_exception_dumps_forensics(tmp_path):
    """An uncaught exception escaping the step loop leaves a black-box
    bundle with the exception context before propagating."""

    class Boom(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)
            self.count = 0

        def launch(self, attrs=None):
            self.count += 1
            if self.count == 3:
                raise RuntimeError("kaboom")

    runtime = Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=str(tmp_path),
        health=True, anomaly_action="warn",
    )
    data = [{"x": np.float32(i)} for i in range(64)]
    with pytest.raises(RuntimeError, match="kaboom"):
        rt.Launcher(
            [rt.Looper([rt.Dataset(data, batch_size=8, fuse_gather=False),
                        Boom()], tag="train", progress=False)],
            num_epochs=1, runtime=runtime,
        ).launch()
    bundles = glob.glob(
        str(tmp_path / "runs" / "telemetry" / "blackbox" / "*")
    )
    assert len(bundles) == 1
    manifest = json.load(open(os.path.join(bundles[0], "blackbox.json")))
    assert manifest["reason"] == "exception_RuntimeError"
    assert "kaboom" in manifest["extra"]["exception"]
    assert manifest["extra"]["tag"] == "train"


def test_health_state_checkpoints_and_resumes(tmp_path):
    """The sentinel state rides the model checkpoint; a pre-health
    checkpoint (no health leaves) still restores with health enabled."""
    ckpt_dir = str(tmp_path / "ckpt")
    clean = _poisoned_data(nan_from=0, nan_to=0)  # nothing poisoned

    # Save WITHOUT health (the checkpoint carries no health/* leaves).
    runtime = Runtime(mesh_shape={"data": 8}, seed=0,
                      project_dir=str(tmp_path))
    launcher, module, _ = _tree(
        runtime, tmp_path, num_epochs=1, data=clean,
        extra=(rt.Checkpointer(output_dir=ckpt_dir, save_every=4),),
    )
    launcher.launch()
    assert os.path.isdir(os.path.join(ckpt_dir, "4"))

    # Resume WITH health: the optional health leaves keep their fresh
    # live values and the sentinels run from there.
    runtime2 = Runtime(mesh_shape={"data": 8}, seed=0,
                       project_dir=str(tmp_path), health=True)
    launcher2, module2, _ = _tree(
        runtime2, tmp_path, num_epochs=1, data=clean,
        extra=(rt.Checkpointer(output_dir=ckpt_dir, save_every=1000,
                               resume_from=os.path.join(ckpt_dir, "4"),
                               resume_capsules=False),),
    )
    launcher2.launch()
    summary = runtime2.health.summary()
    assert summary["last_good_step"] is not None
    assert summary["anomalies"] == 0


def test_metric_publish_counts_nonfinite_host_scalars(tmp_path):
    runtime = Runtime(seed=0, project_dir=str(tmp_path), health=True)
    try:
        metric = rt.Metric.__new__(rt.Metric)
        rt.Capsule.__init__(metric)
        metric.bind(runtime)
        metric.publish(None, "val/acc", float("nan"))
        metric.publish(None, "val/acc", 0.5)
        counters = runtime.telemetry.registry.snapshot()["counters"]
        assert counters["health/nonfinite_metrics"] == 1.0
        assert runtime.health.summary()["nonfinite_metrics"] == 1
    finally:
        runtime.end_training()
