"""rocket_tpu.obs.reqtrace — per-request tail-latency tracing contracts:
timeline event ordering + the exact phase partition, eviction-resume
spanning one timeline, exemplar selection math and shard persistence,
SLO-violation → exemplar linkage through the exporter + flight recorder,
and the `obs timeline` CLI exit/json contracts.

Deliberately jax-free (like test_export.py): the tracer is stdlib dicts
driven with synthetic clocks — no engine, no backend. The live-engine
overhead contract (reqtrace on vs off: identical wave counts, zero extra
device transfers, identical outputs) lives in test_serve.py.
"""

import json

import pytest

from rocket_tpu.obs.export import ExportConfig, TelemetryExporter, read_shard_file
from rocket_tpu.obs.reqtrace import (
    EXEMPLARS_FILE,
    REQTRACE_FILE,
    RequestTracer,
    aggregate_phases,
    read_timeline_dir,
    render_aggregate,
    render_waterfall,
    timeline_segments,
)
from rocket_tpu.obs.telemetry import Telemetry


def _drive(tracer, rid, t0, *, queue=0.5, prefill=0.4, waves=((0.2, 1), (0.2, 1))):
    """One full request lifecycle on a synthetic clock: submit at t0,
    admit after `queue`, first wave after `prefill`, then one wave per
    (dt, n) pair, finishing on the last. Returns the finish time."""
    tracer.on_submit(rid, t0, prompt_len=4, max_new_tokens=len(waves))
    t = t0 + queue
    tracer.on_admit(rid, t, slot=0, ctx_len=4)
    tracer.on_prefill(rid, t, 0, 3)
    for i, (dt, n) in enumerate(waves):
        # The first wave lands `prefill` after admit; its dt is unused
        # (ttft = queue + prefill by construction).
        t = (t0 + queue + prefill) if i == 0 else t + dt
        seq = tracer.on_dispatch(occupancy=1, t=t - 0.01)
        tracer.on_harvest(seq, t)
        tracer.on_tokens(rid, seq, n, t)
    tracer.on_finish(rid, t)
    return t


# -- timeline contract ------------------------------------------------------


def test_lifecycle_event_ordering_and_exact_phase_partition():
    tracer = RequestTracer()
    tracer.on_submit(1, 10.0, prompt_len=4, max_new_tokens=2)
    tracer.on_admit(1, 10.5, slot=3, ctx_len=4)
    tracer.on_prefill(1, 10.6, 0, 3)
    seq = tracer.on_dispatch(occupancy=2, t=10.7, waves=1)
    tracer.on_harvest(seq, 10.9)
    tracer.on_tokens(1, seq, 1, 10.9)
    seq2 = tracer.on_dispatch(occupancy=2, t=10.95)
    tracer.on_harvest(seq2, 11.1)
    tracer.on_tokens(1, seq2, 1, 11.1)
    tracer.on_finish(1, 11.1)

    rec = tracer.timeline(1)
    assert rec["final"] and rec["rid"] == 1 and rec["tokens"] == 2
    assert rec["ttft_s"] == pytest.approx(0.9)
    assert rec["total_s"] == pytest.approx(1.1)
    # The phase partition sums EXACTLY to the measured wall time.
    phases = rec["phases"]
    assert phases["queue_s"] == pytest.approx(0.5)
    assert phases["prefill_s"] == pytest.approx(0.4)
    assert phases["decode_s"] == pytest.approx(0.2)
    assert phases["preempted_s"] == 0.0
    assert sum(phases.values()) == pytest.approx(rec["total_s"], rel=1e-6)
    # Event stream: lifecycle order, relative times monotone.
    kinds = [e["ev"] for e in rec["events"]]
    assert kinds == ["submit", "admit", "prefill", "wave", "wave", "finish"]
    times = [e["t"] for e in rec["events"]]
    assert times == sorted(times) and times[0] == 0.0
    # The shared wave record's join fields ride the participation event.
    wave = rec["events"][3]
    assert wave["seq"] == seq and wave["occ"] == 2
    assert wave["lat"] == pytest.approx(0.2)
    # ITL gap between the two harvests, attributed to waiting-on-wave.
    assert rec["itl"]["worst_gap_s"] == pytest.approx(0.2)
    assert rec["itl"]["worst_gap_kind"] == "waiting"
    # Segments partition [0, total] with no holes.
    segs = timeline_segments(rec)
    assert segs[0][1] == 0.0 and segs[-1][2] == pytest.approx(1.1)
    for (_, _, end), (_, start, _) in zip(segs, segs[1:]):
        assert start == pytest.approx(end)


def test_eviction_resume_is_one_timeline_spanning_both_residencies():
    tracer = RequestTracer()
    tracer.on_submit(7, 0.0, prompt_len=2, max_new_tokens=8)
    tracer.on_admit(7, 1.0, slot=0, ctx_len=2)
    s0 = tracer.on_dispatch(occupancy=1, t=1.9)
    tracer.on_harvest(s0, 2.0)
    tracer.on_tokens(7, s0, 1, 2.0)
    tracer.on_evict(7, 3.0)
    # Second residency: re-admitted with progress folded into ctx.
    tracer.on_admit(7, 5.0, slot=1, ctx_len=3, resumed=True)
    s1 = tracer.on_dispatch(occupancy=1, t=5.9)
    tracer.on_harvest(s1, 6.0)
    tracer.on_tokens(7, s1, 1, 6.0)
    tracer.on_finish(7, 7.0)

    rec = tracer.timeline(7)
    assert rec["preemptions"] == 1 and rec["tokens"] == 2
    kinds = [e["ev"] for e in rec["events"]]
    assert kinds == ["submit", "admit", "wave", "evict", "admit", "wave",
                     "finish"]
    assert rec["events"][4]["resumed"] is True
    phases = rec["phases"]
    assert phases["queue_s"] == pytest.approx(1.0)    # 0 -> first admit
    assert phases["preempted_s"] == pytest.approx(2.0)  # evict -> re-admit
    assert phases["prefill_s"] == pytest.approx(2.0)  # 1->2 plus 5->6
    assert phases["decode_s"] == pytest.approx(2.0)   # 2->3 plus 6->7
    assert sum(phases.values()) == pytest.approx(7.0)
    # The eviction gap dominates ITL and is attributed to descheduling.
    assert rec["itl"]["worst_gap_s"] == pytest.approx(4.0)
    assert rec["itl"]["worst_gap_kind"] == "descheduled"
    assert rec["itl"]["descheduled_s"] == pytest.approx(4.0)
    # The waterfall shows the preemption hole.
    assert ("preempted", 3.0, 5.0) in [
        (k, round(a, 6), round(b, 6)) for k, a, b in timeline_segments(rec)
    ]
    assert "x" in render_waterfall(rec)


def test_event_cap_compacts_waves_but_keeps_exact_accounting():
    tracer = RequestTracer(max_events=16)
    tracer.on_submit(1, 0.0, prompt_len=2, max_new_tokens=100)
    tracer.on_admit(1, 1.0, slot=0, ctx_len=2)
    t = 1.0
    for _ in range(100):
        t += 0.5
        seq = tracer.on_dispatch(occupancy=1, t=t - 0.1)
        tracer.on_harvest(seq, t)
        tracer.on_tokens(1, seq, 1, t)
    tracer.on_finish(1, t)
    rec = tracer.timeline(1)
    assert len(rec["events"]) <= 16
    assert rec["tokens"] == 100
    spans = [e for e in rec["events"] if e["ev"] == "wave_span"]
    assert spans, "coalesced wave spans expected past the event cap"
    assert sum(e["n"] for e in rec["events"]
               if e["ev"] in ("wave", "wave_span")) == 100
    # Incremental accounting is immune to compaction.
    assert rec["phases"]["prefill_s"] == pytest.approx(0.5)  # 1.0 -> 1.5
    assert rec["phases"]["decode_s"] == pytest.approx(49.5)
    assert sum(rec["phases"].values()) == pytest.approx(rec["total_s"])


def test_release_drops_live_and_finished_timelines():
    tracer = RequestTracer()
    _drive(tracer, 1, 0.0)
    tracer.on_submit(2, 5.0, prompt_len=1, max_new_tokens=1)
    assert tracer.timeline(1) is not None
    assert tracer.timeline(2) is not None and not tracer.timeline(2)["final"]
    tracer.release(1)
    tracer.release(2)
    assert tracer.timeline(1) is None and tracer.timeline(2) is None


# -- exemplar selection + persistence ---------------------------------------


def test_exemplar_selection_math_and_shard_persistence(tmp_path):
    tracer = RequestTracer(exemplar_k=2)
    # ttft (queue + prefill), slowest first: 3, 2, 1 — worst inter-wave
    # gap: 2, 3, 1.
    _drive(tracer, 1, 0.0, queue=0.1, waves=((0.1, 1), (0.1, 1)))
    _drive(tracer, 2, 10.0, queue=0.2, waves=((0.1, 1), (3.0, 1)))
    _drive(tracer, 3, 20.0, queue=5.0, waves=((0.1, 1), (1.0, 1)))
    out = tracer.flush(str(tmp_path))
    assert out["finished"] == 3 and out["persisted"] == 3
    assert tracer.last_window["ttft"] == [3, 2]
    assert tracer.last_window["itl_gap"] == [2, 3]
    assert out["exemplars"] == tracer.last_window
    # Shard discipline: both files are crash-readable JSONL.
    reqtrace = read_shard_file(str(tmp_path / "telemetry" / REQTRACE_FILE))
    assert sorted(r["rid"] for r in reqtrace) == [1, 2, 3]
    exemplars = read_shard_file(str(tmp_path / "telemetry" / EXEMPLARS_FILE))
    tagged = {(r["exemplar"]["by"], r["exemplar"]["rank"]): r["rid"]
              for r in exemplars}
    assert tagged[("ttft", 0)] == 3 and tagged[("itl_gap", 0)] == 2
    # The next window starts empty — nothing re-persisted.
    again = tracer.flush(str(tmp_path))
    assert again["finished"] == 0 and again["persisted"] == 0
    assert again["exemplars"] == {"ttft": [], "itl_gap": []}
    # The reader dedupes exemplar copies into tags on one record.
    records = read_timeline_dir(str(tmp_path))
    by_rid = {r["rid"]: r for r in records}
    assert len(records) == 3
    # rids 2 and 3 are tail exemplars on BOTH dimensions with k=2;
    # rid 1 is ordinary.
    assert by_rid[2]["exemplar_by"] == ["ttft", "itl_gap"]
    assert by_rid[3]["exemplar_by"] == ["ttft", "itl_gap"]
    assert by_rid[1]["exemplar_by"] == []


def test_aggregate_phase_fractions():
    tracer = RequestTracer()
    _drive(tracer, 1, 0.0)
    _drive(tracer, 2, 10.0, queue=1.0)
    agg = aggregate_phases([tracer.timeline(1), tracer.timeline(2)])
    assert agg["requests"] == 2
    fracs = [agg[k] for k in ("queue_frac", "prefill_frac", "decode_frac",
                              "preempted_frac")]
    assert sum(fracs) == pytest.approx(1.0, abs=1e-3)
    assert "worst" in render_aggregate(
        [tracer.timeline(1), tracer.timeline(2)]
    )
    assert aggregate_phases([]) is None


# -- SLO-violation -> exemplar linkage --------------------------------------


def test_slo_violation_carries_window_exemplars_into_flight(tmp_path):
    from rocket_tpu.obs.flight import FlightRecorder

    spec_file = tmp_path / "slo.json"
    spec_file.write_text(json.dumps({"version": 1, "slos": [
        {"name": "steps_floor", "kind": "gauge_min",
         "metric": "perf/steps_per_sec", "objective": 100.0},
    ]}))
    telemetry = Telemetry(enabled=True, out_dir=str(tmp_path / "run"))
    telemetry.registry.gauge("perf/steps_per_sec").set(5.0)  # violating
    telemetry.flight = FlightRecorder(telemetry=telemetry)
    tracer = RequestTracer()
    _drive(tracer, 11, 0.0, queue=2.0)
    _drive(tracer, 12, 1.0, queue=0.1)
    telemetry.reqtrace = tracer
    exporter = TelemetryExporter(
        telemetry,
        ExportConfig(enabled=True, slo_path=str(spec_file)),
        identity={"rank": 0, "hostname": "testhost", "pid": 1},
    )
    record = exporter.tick()
    # The exporter drained the tracer's window into the shard dir...
    assert record["reqtrace"]["finished"] == 2
    assert (tmp_path / "run" / "telemetry" / REQTRACE_FILE).exists()
    # ...and the violation names the window's exemplar request ids,
    # both on the shard record and in the flight anomaly.
    verdict, = [s for s in record["slo"] if s["name"] == "steps_floor"]
    assert verdict["violated"]
    assert verdict["exemplars"]["ttft"] == [11, 12]
    anomaly = telemetry.flight.anomalies()[-1]
    assert anomaly["kind"] == "slo_violation"
    assert anomaly["exemplars"]["ttft"] == [11, 12]


# -- the obs timeline CLI ---------------------------------------------------


def test_timeline_cli_contracts(tmp_path, capsys):
    from rocket_tpu.obs.__main__ import main

    run = tmp_path / "run"
    tracer = RequestTracer()
    _drive(tracer, 1, 0.0, queue=4.0)
    _drive(tracer, 2, 1.0)
    _drive(tracer, 3, 2.0)
    tracer.flush(str(run))

    assert main(["timeline", str(run), "--slowest", "2"]) == 0
    text = capsys.readouterr().out
    assert "request 1" in text and "queue" in text
    assert main(["timeline", str(run), "--request", "2"]) == 0
    assert "request 2" in capsys.readouterr().out

    assert main(["timeline", str(run), "--format", "json"]) == 0
    doc = json.loads(capsys.readouterr().out)
    assert sorted(doc) == ["aggregate", "requests"]
    assert len(doc["requests"]) == 3
    assert doc["aggregate"]["requests"] == 3
    for rec in doc["requests"]:
        # The rendered phase durations sum to the measured wall time.
        assert sum(rec["phases"].values()) == pytest.approx(
            rec["total_s"], rel=0.05
        )

    assert main(["timeline", str(run), "--request", "999"]) == 2
    assert main(["timeline", str(tmp_path / "void")]) == 2


def test_top_renders_slo_column(tmp_path):
    """Satellite: obs top shows the obs/slo/* gauges already riding the
    shards as a per-rank SLO column."""
    from rocket_tpu.obs.__main__ import _render_top, _slo_rows

    latest = {
        0: {"t_unix": 0, "metrics": {"gauges": {
            "obs/slo/itl_p99/burn_rate": 2.5,
            "obs/slo/itl_p99/violated": 1.0,
        }}},
        1: {"t_unix": 0, "metrics": {"gauges": {
            "obs/slo/itl_p99/burn_rate": 0.4,
            "obs/slo/itl_p99/violated": 0.0,
        }}},
    }
    rows = _slo_rows(latest)
    assert rows == [("itl_p99", 0, 2.5, True), ("itl_p99", 1, 0.4, False)]
    frame = _render_top(latest)
    assert "slo (per rank" in frame and "VIOLATED" in frame and "ok" in frame
