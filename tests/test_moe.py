"""Mixture-of-Experts: routing correctness, capacity, expert-parallel
training on a ('data', 'expert') mesh."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import TokenDataset
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    next_token_loss,
)
from rocket_tpu.nn.moe import MoE
from rocket_tpu.parallel.sharding import combine_rules, gpt2_tp_rules, moe_rules
from rocket_tpu.runtime.context import Runtime


def test_moe_shapes_and_aux():
    moe = MoE(dim=16, hidden=32, num_experts=4, top_k=2)
    params = moe.init_params(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 8, 16))
    y, out = moe.apply({"params": params, "state": {}}, x)
    assert y.shape == x.shape
    aux = float(out["aux_loss"])
    # Perfectly balanced routing gives aux = 1; any routing stays positive
    # and finite.
    assert 0.0 < aux < 8.0


def test_moe_top1_matches_manual_expert():
    """With top_k=1 and ample capacity, each token's output equals its
    chosen expert's FFN applied directly."""
    moe = MoE(dim=8, hidden=16, num_experts=2, top_k=1, capacity_factor=4.0)
    params = moe.init_params(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (1, 6, 8))
    y, _ = moe.apply({"params": params, "state": {}}, x)

    logits, _ = moe.router.apply(
        {"params": params["router"], "state": {}}, x.reshape(6, 8)
    )
    choice = np.asarray(jnp.argmax(logits, axis=-1))
    ex = params["experts"]
    for t in range(6):
        e = int(choice[t])
        h = jax.nn.gelu(x[0, t] @ ex["w_in"][e] + ex["b_in"][e])
        ref = h @ ex["w_out"][e] + ex["b_out"][e]
        np.testing.assert_allclose(
            np.asarray(y[0, t]), np.asarray(ref), rtol=1e-4, atol=1e-5
        )


def test_moe_capacity_drops_overflow_tokens():
    """Tokens past an expert's capacity fall back to zero output (residual
    path in the block): force every token onto expert 0 via the router."""
    moe = MoE(dim=4, hidden=8, num_experts=2, top_k=1, capacity_factor=0.5)
    params = moe.init_params(jax.random.key(0))
    # Rig the router so expert 0 always wins.
    params["router"] = {"w": jnp.zeros((4, 2)).at[:, 0].set(0.0).at[:, 1].set(-1e9)}
    x = jnp.ones((1, 8, 4))
    y, _ = moe.apply({"params": params, "state": {}}, x)
    # capacity = 0.5 * 8 * 1 / 2 = 2 slots on expert 0; identical tokens, so
    # kept rows are identical and the overflow rows are exactly zero.
    nonzero = np.asarray(jnp.any(jnp.abs(y[0]) > 1e-9, axis=-1))
    assert nonzero.sum() == 2, nonzero


def test_moe_validation():
    with pytest.raises(ValueError, match="top_k"):
        MoE(dim=4, hidden=8, num_experts=2, top_k=3)


@pytest.mark.slow
@pytest.mark.parametrize("scan", [False, True])
def test_moe_lm_trains_expert_parallel(tmp_path, scan):
    """A small MoE LM trains on a ('data', 'expert') mesh with the stacked
    expert params sharded over 'expert' and attention optionally stacked."""
    runtime = Runtime(
        mesh_shape={"data": 2, "expert": 4}, seed=0, project_dir=str(tmp_path)
    )
    config = TransformerConfig(
        vocab_size=64, max_seq_len=32, dim=32, num_layers=2, num_heads=4,
        dropout=0.0, num_experts=4, expert_top_k=2, scan_layers=scan,
    )
    model = TransformerLM(config)
    rng = np.random.default_rng(0)
    data = TokenDataset(rng.integers(0, 64, size=32 * 65).astype(np.int32), seq_len=32)
    module = rt.Module(
        model,
        capsules=[rt.Loss(next_token_loss()),
                  rt.Optimizer(optim.adamw(), learning_rate=3e-3)],
        param_sharding=moe_rules(),
    )
    losses, seen = [], {}

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.looper.state.loss is not None:
                losses.append(float(np.asarray(attrs.looper.state.loss)))
            blocks = module.state["params"].get("blocks_stacked") or \
                module.state["params"]["blocks"]["0"]
            seen["spec"] = str(blocks["moe"]["experts"]["w_in"].sharding.spec)

    rt.Launcher(
        [rt.Looper([rt.Dataset(data, batch_size=16, drop_last=True), module, Spy()],
                   tag="train", progress=False)],
        num_epochs=2,
        runtime=runtime,
    ).launch()
    assert "expert" in seen["spec"], seen
    assert losses[-1] < losses[0], (losses[0], losses[-1])


def test_combine_rules_first_match_wins():
    rules = combine_rules(moe_rules(), gpt2_tp_rules())
    # Expert params -> moe rule.
    assert rules(("blocks", "0", "moe", "experts", "w_in"), np.zeros((4, 8, 16))) == (
        "expert", None, None,
    )
    # Attention params -> tp rule.
    assert rules(("blocks", "0", "attn", "qkv", "w"), np.zeros((8, 24))) == (
        None, "model",
    )


@pytest.mark.slow
def test_pipeline_moe_aux_matches_scan_at_m1(tmp_path):
    """MoE through the GPipe trunk: with one microbatch and no data
    sharding the routing groups coincide, so logits AND the aux loss must
    equal the scan-over-layers path exactly."""
    import dataclasses

    import rocket_tpu as rt
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(
        mesh_shape={"pipe": 4}, devices=jax.devices()[:4], seed=0,
        project_dir=str(tmp_path),
    )
    base = TransformerConfig(
        vocab_size=64, max_seq_len=32, dim=32, num_layers=4, num_heads=4,
        dropout=0.0, num_experts=4, expert_top_k=2,
        expert_capacity_factor=2.0, scan_layers=True,
    )
    scan_model = TransformerLM(base)
    pipe_model = TransformerLM(dataclasses.replace(
        base, pipeline_axis="pipe", pipeline_microbatches=1,
    ))
    variables = scan_model.init(jax.random.key(0))
    tokens = {"tokens": jnp.asarray(
        np.random.default_rng(0).integers(0, 64, (4, 32)), jnp.int32)}

    out_scan, _ = scan_model.apply(variables, tokens, mode="eval")
    out_pipe, _ = pipe_model.apply(variables, tokens, mode="eval")
    np.testing.assert_allclose(
        np.asarray(out_scan["logits"]), np.asarray(out_pipe["logits"]),
        rtol=2e-4, atol=2e-4,
    )
    np.testing.assert_allclose(
        np.asarray(out_scan["moe_aux_loss"]),
        np.asarray(out_pipe["moe_aux_loss"]),
        rtol=1e-5,
    )


@pytest.mark.slow
def test_pipeline_moe_trains(tmp_path):
    """pp x MoE end-to-end: a training epoch on a ('data','pipe') mesh with
    the aux loss flowing through the pipeline's with_aux channel."""
    import rocket_tpu as rt
    from rocket_tpu import optim
    from rocket_tpu.data.text import TokenDataset
    from rocket_tpu.models.transformer import (
        TransformerConfig, TransformerLM, next_token_loss,
    )
    from rocket_tpu.parallel.sharding import pipeline_rules
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(mesh_shape={"data": 2, "pipe": 4}, seed=0,
                      project_dir=str(tmp_path))
    config = TransformerConfig(
        vocab_size=64, max_seq_len=16, dim=32, num_layers=4, num_heads=4,
        dropout=0.0, num_experts=4, expert_top_k=2, scan_layers=True,
        pipeline_axis="pipe", pipeline_microbatches=2,
    )
    rng = np.random.default_rng(0)
    data = TokenDataset(rng.integers(0, 64, size=16 * 9).astype(np.int32),
                        seq_len=16)
    losses = []

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            losses.append(attrs.step_metrics.loss)

    rt.Launcher(
        [rt.Looper(
            [rt.Dataset(data, batch_size=8, drop_last=True),
             rt.Module(
                 TransformerLM(config),
                 capsules=[rt.Loss(next_token_loss()),
                           rt.Optimizer(optim.adamw(), learning_rate=1e-3)],
                 param_sharding=pipeline_rules(),
             ),
             Spy()],
            tag="train", progress=False,
        )],
        num_epochs=1,
        runtime=runtime,
    ).launch()
    assert losses and np.isfinite(float(np.asarray(losses[-1])))


@pytest.mark.slow
def test_moe_cached_generation_matches_recompute():
    """MoE now decodes through the KV cache (round-3 verdict ask #4): with
    ample expert capacity the cached and recompute paths sample identical
    tokens."""
    from rocket_tpu.models.transformer import (
        TransformerConfig, TransformerLM, generate,
    )

    config = TransformerConfig(
        vocab_size=64, max_seq_len=32, dim=32, num_layers=2, num_heads=4,
        dropout=0.0, num_experts=4, expert_top_k=2,
        # Ample capacity: no token ever drops, so per-step routing (each
        # generated token alone in its group) matches full-prefix routing.
        expert_capacity_factor=8.0,
    )
    model = TransformerLM(config)
    variables = model.init(jax.random.key(0))
    prompt = jnp.asarray(
        np.random.default_rng(1).integers(0, 64, (2, 5)), jnp.int32)
    out_cache = generate(
        model, variables, prompt, 8, key=jax.random.key(2),
        temperature=1.0, use_cache=True,
    )
    out_recompute = generate(
        model, variables, prompt, 8, key=jax.random.key(2),
        temperature=1.0, use_cache=False,
    )
    np.testing.assert_array_equal(np.asarray(out_cache), np.asarray(out_recompute))


def test_pipeline_over_composes_tp_and_pipe_axes():
    import numpy as np

    from rocket_tpu.parallel.sharding import gpt2_tp_rules, pipeline_over

    rules = pipeline_over(gpt2_tp_rules())
    leaf3 = np.zeros((4, 32, 96))  # stacked qkv kernel (L, D, 3D)
    assert rules(("blocks_stacked", "attn", "qkv", "w"), leaf3) == \
        ("pipe", None, "model")
    # Stacked leaf the inner rules leave alone: layer dim still pipelined.
    assert rules(("blocks_stacked", "ln1", "g"), np.zeros((4, 32))) == \
        ("pipe", None)
    # Non-stacked leaves follow the inner rules untouched.
    assert rules(("wte", "table"), np.zeros((64, 32))) == ("model", None)


def test_scatter_dispatch_matches_einsum():
    """The linear-in-T scatter dispatch computes EXACTLY the einsum path's
    output (same routing, same drops) — fwd and grads."""
    from rocket_tpu.nn.moe import MoE

    dim, hidden, e, k = 16, 32, 4, 2
    x = jax.random.normal(jax.random.key(0), (3, 24, dim))
    moe_e = MoE(dim, hidden, e, top_k=k, capacity_factor=1.0, dispatch="einsum")
    moe_s = MoE(dim, hidden, e, top_k=k, capacity_factor=1.0, dispatch="scatter")
    params = moe_e.init_params(jax.random.key(1))

    y_e, aux_e = moe_e.apply({"params": params, "state": {}}, x)
    y_s, aux_s = moe_s.apply({"params": params, "state": {}}, x)
    np.testing.assert_allclose(np.asarray(y_e), np.asarray(y_s), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(aux_e["aux_loss"]), np.asarray(aux_s["aux_loss"])
    )

    def loss(mode):
        moe = MoE(dim, hidden, e, top_k=k, capacity_factor=1.0, dispatch=mode)
        return lambda p, x: (moe.apply({"params": p, "state": {}}, x)[0] ** 2).sum()

    g_e = jax.grad(loss("einsum"))(params, x)
    g_s = jax.grad(loss("scatter"))(params, x)
    for a, b in zip(jax.tree.leaves(g_e), jax.tree.leaves(g_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_scatter_dispatch_lm_trains(tmp_path):
    """expert_dispatch='scatter' end-to-end through a training step."""
    import rocket_tpu as rt
    from rocket_tpu import optim
    from rocket_tpu.data.text import TokenDataset
    from rocket_tpu.models.transformer import (
        TransformerConfig, TransformerLM, next_token_loss,
    )
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(seed=0, project_dir=str(tmp_path))
    config = TransformerConfig(
        vocab_size=64, max_seq_len=16, dim=32, num_layers=2, num_heads=4,
        dropout=0.0, num_experts=4, expert_top_k=2, expert_dispatch="scatter",
    )
    rng = np.random.default_rng(0)
    data = TokenDataset(rng.integers(0, 64, size=16 * 9).astype(np.int32), seq_len=16)
    losses = []

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            losses.append(float(np.asarray(attrs.step_metrics.loss)))

    rt.Launcher(
        [rt.Looper(
            [rt.Dataset(data, batch_size=8, drop_last=True),
             rt.Module(TransformerLM(config),
                       capsules=[rt.Loss(next_token_loss()),
                                 rt.Optimizer(optim.adamw(), learning_rate=1e-3)]),
             Spy()],
            tag="train", progress=False,
        )],
        num_epochs=1,
        runtime=runtime,
    ).launch()
    assert losses and np.isfinite(losses[-1])


def test_dropless_dispatch_matches_einsum_when_nothing_drops():
    """The sort/ragged_dot dropless dispatch computes the einsum path's
    output exactly when capacity is ample (no overflow drops) — fwd and
    grads. With finite capacity the modes legitimately differ (dropless
    never drops), so parity is asserted at capacity_factor=e/k."""
    dim, hidden, e, k = 16, 32, 4, 2
    x = jax.random.normal(jax.random.key(0), (3, 24, dim))
    # capacity = cf*t*k/e with cf = e/k -> capacity = t: no pair can drop.
    moe_e = MoE(dim, hidden, e, top_k=k, capacity_factor=e / k,
                dispatch="einsum")
    moe_d = MoE(dim, hidden, e, top_k=k, dispatch="dropless")
    params = moe_e.init_params(jax.random.key(1))

    y_e, aux_e = moe_e.apply({"params": params, "state": {}}, x)
    y_d, aux_d = moe_d.apply({"params": params, "state": {}}, x)
    assert float(aux_e["frac_dropped"]) == 0.0
    assert float(aux_d["frac_dropped"]) == 0.0
    np.testing.assert_allclose(np.asarray(y_e), np.asarray(y_d), atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(aux_e["aux_loss"]), np.asarray(aux_d["aux_loss"])
    )

    def loss(moe):
        return lambda p, x: (moe.apply({"params": p, "state": {}}, x)[0] ** 2).sum()

    g_e = jax.grad(loss(moe_e))(params, x)
    g_d = jax.grad(loss(moe_d))(params, x)
    for a, b in zip(jax.tree.leaves(g_e), jax.tree.leaves(g_d)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_dropless_dispatch_jits_and_takes_bf16():
    """dropless under jit with bf16 activations: static shapes (data-
    dependent group COUNTS only), output finite, dtype preserved."""
    dim, hidden, e, k = 16, 32, 4, 2
    moe = MoE(dim, hidden, e, top_k=k, dispatch="dropless")
    params = moe.init_params(jax.random.key(1))
    x = jax.random.normal(jax.random.key(0), (2, 16, dim), jnp.bfloat16)

    @jax.jit
    def f(p, x):
        return moe.apply({"params": p, "state": {}}, x)

    y, aux = f(params, x)
    assert y.dtype == jnp.bfloat16
    assert y.shape == x.shape
    assert np.isfinite(np.asarray(y, np.float32)).all()


def test_dropless_dispatch_lm_trains(tmp_path):
    """expert_dispatch='dropless' end-to-end through a training step."""
    config = TransformerConfig(
        vocab_size=64, max_seq_len=16, dim=32, num_layers=2, num_heads=4,
        dropout=0.0, num_experts=4, expert_top_k=2,
        expert_dispatch="dropless",
    )
    runtime = Runtime(mesh_shape={"data": 8}, seed=0,
                      project_dir=str(tmp_path))
    tokens = np.random.default_rng(0).integers(
        0, 64, size=16 * 65).astype(np.int32)
    module = rt.Module(
        TransformerLM(config),
        capsules=[rt.Loss(next_token_loss()),
                  rt.Optimizer(optim.adamw(), learning_rate=1e-3)],
    )
    steps = []

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            steps.append(float(np.asarray(attrs.step_metrics.loss)))

    tree = rt.Launcher(
        [rt.Looper(
            [rt.Dataset(TokenDataset(tokens, seq_len=16), batch_size=8),
             module, Spy()],
            tag="train", progress=False)],
        num_epochs=2, runtime=runtime,
    )
    tree.launch()
    assert len(steps) >= 16 and np.isfinite(steps[-1])
