"""Structural kernel families (ISSUE 14): interpret-mode fwd+bwd parity
of each fused pallas variant against its reference path, and the three
call-site seams' contracts:

* with tables absent (or ``ROCKET_TPU_TUNE=0``) every seam is BITWISE
  the pre-existing composition — the acceptance criterion;
* the force-override envs engage each fused variant on CPU (interpret
  mode) and the results hold the tuner's parity tolerance;
* the padded group layout behind gather-gmm is exact under ragged and
  degenerate (empty-expert) routings.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from rocket_tpu.tune.space import TUNE_SPACES
from rocket_tpu.tune.tuner import check_parity

RNG = np.random.default_rng(0)


def _space_parity(kernel, ref, got, dtype):
    """check_parity under the kernel's OWN sweep contract — the
    TuneSpace parity_tol override when one is declared (fused_conv and
    block_attn scope a wider f32 bound for their legitimately
    reassociated reductions)."""
    return check_parity(
        ref, got, dtype, tol=TUNE_SPACES[kernel].parity_tol.get(dtype)
    )


def _value_and_grads(fn, *args, argnums=None):
    argnums = tuple(range(len(args))) if argnums is None else argnums

    def loss(*a):
        out = fn(*a)
        leaves = jax.tree.leaves(out)
        return sum((leaf.astype(jnp.float32) ** 2).sum()
                   for leaf in leaves), out

    (_, out), grads = jax.value_and_grad(
        loss, argnums=argnums, has_aux=True
    )(*args)
    return tuple(jax.tree.leaves(out)) + tuple(jax.tree.leaves(grads))


def _pallas_calls(fn, *args) -> int:
    # Fresh wrapper per call: make_jaxpr shares jit's trace cache keyed
    # on function identity, and the ROCKET_TPU_* force-overrides are
    # read at TRACE time — a cached trace would ignore an env flip.
    return str(jax.make_jaxpr(lambda *a: fn(*a))(*args)).count(
        "pallas_call"
    )


# -- fused conv epilogue (fused_conv) ----------------------------------------


def _bn_operands(b=8, hw=8, c=16, dtype=jnp.float32):
    x = jnp.asarray(
        RNG.normal(size=(b, hw, hw, c)).astype(np.float32) + 0.3
    ).astype(dtype)
    scale = jnp.asarray(
        1.0 + 0.1 * RNG.normal(size=(c,)).astype(np.float32)
    )
    bias = jnp.asarray(0.1 * RNG.normal(size=(c,)).astype(np.float32))
    return x, scale, bias


@pytest.mark.parametrize("schedule", ["twopass", "stats_xla"])
@pytest.mark.parametrize("act", [True, False])
def test_fused_bn_act_parity(schedule, act):
    """Both schedules of the fused BN(+relu) kernel match the
    `_bn_train` + relu reference — outputs, stats AND grads."""
    from rocket_tpu.ops.fused_conv import fused_bn_act, reference_bn_act

    x, scale, bias = _bn_operands()
    ref = _value_and_grads(
        lambda *a: reference_bn_act(*a, 1e-5, act), x, scale, bias
    )
    got = _value_and_grads(
        lambda *a: fused_bn_act(
            *a, eps=1e-5, act=act, schedule=schedule, block_rows=128,
            interpret=True,
        ),
        x, scale, bias,
    )
    ok, err = _space_parity("fused_conv", ref, got, "float32")
    assert ok, (schedule, act, err)


def test_fused_bn_act_bf16_parity():
    from rocket_tpu.ops.fused_conv import fused_bn_act, reference_bn_act

    x, scale, bias = _bn_operands(b=16, hw=8, c=32, dtype=jnp.bfloat16)
    ref = _value_and_grads(
        lambda *a: reference_bn_act(*a, 1e-5, True), x, scale, bias
    )
    got = _value_and_grads(
        lambda *a: fused_bn_act(*a, eps=1e-5, act=True, block_rows=256,
                                interpret=True),
        x, scale, bias,
    )
    ok, err = _space_parity("fused_conv", ref, got, "bfloat16")
    assert ok, err


def test_fused_bn_act_rejects_bad_config():
    from rocket_tpu.ops.fused_conv import fused_bn_act

    x, scale, bias = _bn_operands()
    with pytest.raises(ValueError, match="tile block_rows"):
        fused_bn_act(x, scale, bias, block_rows=384, interpret=True)
    with pytest.raises(ValueError, match="unknown schedule"):
        fused_bn_act(x, scale, bias, schedule="retired", block_rows=128,
                     interpret=True)


def test_bn_act_seam_default_is_bitwise_reference():
    """With no table entry the seam IS `_bn_train` + relu — bitwise,
    fwd and grads (the acceptance criterion)."""
    from rocket_tpu.nn.layers import _bn_train, bn_act_train

    x, scale, bias = _bn_operands()

    def seam(x, scale, bias):
        return bn_act_train(x, scale, bias, 1e-5, act=True)

    def manual(x, scale, bias):
        y, stats = _bn_train(x, scale, bias, 1e-5)
        return jax.nn.relu(y), stats

    a = _value_and_grads(seam, x, scale, bias)
    b = _value_and_grads(manual, x, scale, bias)
    for left, right in zip(a, b):
        np.testing.assert_array_equal(np.asarray(left), np.asarray(right))
    assert _pallas_calls(seam, x, scale, bias) == 0


def test_bn_act_seam_engages_under_force(monkeypatch):
    from rocket_tpu.nn.layers import bn_act_train

    x, scale, bias = _bn_operands()

    def seam(x, scale, bias):
        return bn_act_train(x, scale, bias, 1e-5, act=True)

    ref = _value_and_grads(seam, x, scale, bias)
    monkeypatch.setenv("ROCKET_TPU_FUSED_CONV", "pallas")
    assert _pallas_calls(seam, x, scale, bias) == 1
    got = _value_and_grads(seam, x, scale, bias)
    ok, err = _space_parity("fused_conv", ref, got, "float32")
    assert ok, err


def test_batchnorm_apply_unchanged_and_act_folds():
    """`BatchNorm.apply` stays op-identical to the pre-seam composition
    and `apply_act(act=True)` == relu(apply(...)) bitwise on the
    default path, train AND eval."""
    from rocket_tpu.nn.layers import BatchNorm, _bn_train

    bn = BatchNorm(16)
    x, scale, bias = _bn_operands(c=16)
    variables = {
        "params": {"scale": scale, "bias": bias},
        "state": {"mean": jnp.zeros(16), "var": jnp.ones(16)},
    }
    for mode in ("train", "eval"):
        y_plain, _ = bn.apply(variables, x, mode=mode)
        y_act, _ = bn.apply_act(variables, x, mode=mode, act=True)
        np.testing.assert_array_equal(
            np.asarray(jax.nn.relu(y_plain)), np.asarray(y_act)
        )
    y_train, state = bn.apply(variables, x, mode="train")
    y_ref, stats = _bn_train(x, scale, bias, bn.eps)
    np.testing.assert_array_equal(np.asarray(y_train), np.asarray(y_ref))
    mean = jax.lax.stop_gradient(stats)[..., 0]
    np.testing.assert_array_equal(
        np.asarray(state["mean"]),
        np.asarray(bn.momentum * variables["state"]["mean"]
                   + (1 - bn.momentum) * mean),
    )


def test_resnet_block_default_has_no_pallas_and_act_matches():
    """The resnet wiring keeps the default program pallas-free, and the
    folded-act _ConvBN equals relu(unfused _ConvBN) bitwise."""
    from rocket_tpu.models.resnet import _BasicBlock, _ConvBN

    x = jnp.asarray(RNG.normal(size=(4, 8, 8, 16)).astype(np.float32))
    cb_act = _ConvBN(16, 16, 3, act=True)
    cb_plain = _ConvBN(16, 16, 3)
    v = cb_act.init(jax.random.key(0))
    y_act, _ = cb_act.apply(v, x, mode="train")
    y_plain, _ = cb_plain.apply(v, x, mode="train")
    np.testing.assert_array_equal(
        np.asarray(y_act), np.asarray(jax.nn.relu(y_plain))
    )
    blk = _BasicBlock(16, 16, 1)
    vb = blk.init(jax.random.key(1))
    assert _pallas_calls(
        lambda x: blk.apply(vb, x, mode="train")[0], x
    ) == 0


# -- whole-block attention half (block_attn) ---------------------------------


def _block_operands(b=4, t=64, d=128, dtype=jnp.float32):
    x = jnp.asarray(
        RNG.normal(size=(b, t, d)).astype(np.float32) * 0.5
    ).astype(dtype)
    ln_s = jnp.asarray(1.0 + 0.1 * RNG.normal(size=(d,)).astype(np.float32))
    ln_b = jnp.asarray(0.1 * RNG.normal(size=(d,)).astype(np.float32))
    wqkv = jnp.asarray(
        RNG.normal(size=(d, 3 * d)).astype(np.float32) * d ** -0.5
    )
    bqkv = jnp.asarray(0.01 * RNG.normal(size=(3 * d,)).astype(np.float32))
    wproj = jnp.asarray(
        RNG.normal(size=(d, d)).astype(np.float32) * d ** -0.5
    )
    bproj = jnp.asarray(0.01 * RNG.normal(size=(d,)).astype(np.float32))
    return x, ln_s, ln_b, wqkv, bqkv, wproj, bproj


def test_reference_block_attn_is_bitwise_nn_composition():
    """The kernel's parity baseline IS the model's per-op path: ln1 +
    fused-QKV MHA on the XLA impl, op for op."""
    from rocket_tpu.nn.attention import MultiHeadAttention
    from rocket_tpu.nn.layers import LayerNorm
    from rocket_tpu.ops.fused_block import reference_block_attn

    d, h = 128, 2
    x, ln_s, ln_b, wqkv, bqkv, wproj, bproj = _block_operands(d=d)
    ln = LayerNorm(d)
    attn = MultiHeadAttention(d, h, impl="xla")
    y_nn, _ = ln.apply(
        {"params": {"scale": ln_s, "bias": ln_b}, "state": {}}, x
    )
    y_nn, _ = attn.apply(
        {"params": {"qkv": {"w": wqkv, "b": bqkv},
                    "proj": {"w": wproj, "b": bproj}}, "state": {}},
        y_nn, mode="eval",
    )
    y_ref = reference_block_attn(
        x, ln_s, ln_b, wqkv, bqkv, wproj, bproj, num_heads=h
    )
    np.testing.assert_array_equal(np.asarray(y_nn), np.asarray(y_ref))


@pytest.mark.parametrize("epilogue", ["fused", "separate"])
@pytest.mark.parametrize("block_b", [1, 2, 4])
def test_block_attn_half_parity(epilogue, block_b):
    from rocket_tpu.ops.fused_block import (
        block_attn_half,
        reference_block_attn,
    )

    args = _block_operands()
    ref = _value_and_grads(
        lambda *a: reference_block_attn(*a, num_heads=2, epilogue=epilogue),
        *args,
    )
    got = _value_and_grads(
        lambda *a: block_attn_half(
            *a, num_heads=2, epilogue=epilogue, block_b=block_b,
            interpret=True,
        ),
        *args,
    )
    ok, err = _space_parity("block_attn", ref, got, "float32")
    assert ok, (epilogue, block_b, err)


def test_block_attn_half_bf16_parity():
    from rocket_tpu.ops.fused_block import (
        block_attn_half,
        reference_block_attn,
    )

    args = tuple(
        a.astype(jnp.bfloat16) if i == 0 else a
        for i, a in enumerate(_block_operands())
    )
    ref = _value_and_grads(
        lambda *a: reference_block_attn(*a, num_heads=2), *args
    )
    got = _value_and_grads(
        lambda *a: block_attn_half(*a, num_heads=2, block_b=2,
                                   interpret=True),
        *args,
    )
    ok, err = _space_parity("block_attn", ref, got, "bfloat16")
    assert ok, err


def test_block_attn_half_rejects_bad_config():
    from rocket_tpu.ops.fused_block import block_attn_half

    args = _block_operands()
    with pytest.raises(ValueError, match="unknown epilogue"):
        block_attn_half(*args, num_heads=2, epilogue="retired",
                        interpret=True)
    with pytest.raises(ValueError, match="unsupported shape"):
        block_attn_half(*args, num_heads=2, block_b=3, interpret=True)


def _charlm_block(dropout=0.1):
    from rocket_tpu.models.transformer import Block, TransformerConfig

    config = TransformerConfig(
        vocab_size=64, max_seq_len=64, dim=128, num_layers=2,
        num_heads=2, dropout=dropout,
    )
    blk = Block(config, 0)
    return blk, blk.init_params(jax.random.key(3))


def test_block_seam_default_is_bitwise_reference():
    """With no table entry Block.apply's attention half IS the per-op
    ln1+attn chain — bitwise, train (dropout rng included) and eval."""
    blk, params = _charlm_block()
    x = _block_operands()[0]
    rng = jax.random.key(11)

    def seam(x, mode):
        y, _ = blk.apply({"params": params, "state": {}}, x, mode=mode,
                         rng=rng if mode == "train" else None)
        return y

    def manual(x, mode):
        r = (jax.random.split(jax.random.fold_in(rng, 0), 3)
             if mode == "train" else (None, None, None))
        h, _ = blk.ln1.apply({"params": params["ln1"], "state": {}}, x)
        h, _ = blk.attn.apply(
            {"params": params["attn"], "state": {}}, h, mode=mode,
            rng=r[0],
        )
        if blk.dropout is not None and mode == "train":
            h, _ = blk.dropout.apply({"params": {}, "state": {}}, h,
                                     mode=mode, rng=r[1])
        y = x + h
        h2, _ = blk.ln2.apply({"params": params["ln2"], "state": {}}, y)
        h2 = blk._mlp(params["mlp"], h2)
        if blk.dropout is not None and mode == "train":
            h2, _ = blk.dropout.apply({"params": {}, "state": {}}, h2,
                                      mode=mode, rng=r[2])
        return y + h2

    for mode in ("train", "eval"):
        np.testing.assert_array_equal(
            np.asarray(seam(x, mode)), np.asarray(manual(x, mode))
        )
    assert _pallas_calls(lambda x: seam(x, "eval"), x) == 0


@pytest.mark.parametrize("mode", ["eval", "train"])
def test_block_seam_engages_under_force(mode, monkeypatch):
    """Forced fused impl: one pallas program replaces the chain; parity
    holds in eval (full epilogue) AND train (dropout forces the
    separate-epilogue tail, which must reproduce the reference dropout
    mask exactly — same rng fold, same mask shape)."""
    blk, params = _charlm_block()
    x = _block_operands()[0]
    rng = jax.random.key(11)

    def step(x):
        y, _ = blk.apply({"params": params, "state": {}}, x, mode=mode,
                         rng=rng if mode == "train" else None)
        return y

    ref = _value_and_grads(step, x)
    monkeypatch.setenv("ROCKET_TPU_BLOCK_ATTN", "fused")
    assert _pallas_calls(step, x) == 1
    got = _value_and_grads(step, x)
    ok, err = _space_parity("block_attn", ref, got, "float32")
    assert ok, (mode, err)


def test_block_seam_ineligible_configs_stay_reference(monkeypatch):
    """RMSNorm/rope/GQA/ring blocks never consult the fused path even
    under force — the eligibility gate is static."""
    from rocket_tpu.models.transformer import Block, TransformerConfig

    monkeypatch.setenv("ROCKET_TPU_BLOCK_ATTN", "fused")
    config = TransformerConfig.llama_style(
        vocab_size=64, max_seq_len=64, dim=128, num_layers=2,
        num_heads=2, num_kv_heads=1,
    )
    blk = Block(config, 0)
    params = blk.init_params(jax.random.key(0))
    x = _block_operands()[0]
    assert not blk._block_attn_ok
    assert _pallas_calls(
        lambda x: blk.apply({"params": params, "state": {}}, x,
                            mode="eval")[0], x
    ) == 0


# -- gather-gmm (moe_gmm impl=fused) -----------------------------------------


def _routing(n_tok, e, key=1):
    rng = np.random.default_rng(key)
    pair_expert = jnp.asarray(rng.integers(0, e, size=n_tok).astype(np.int32))
    order = jnp.argsort(pair_expert, stable=True)
    sorted_token = jnp.arange(n_tok, dtype=jnp.int32)[order]
    counts = jnp.bincount(pair_expert, length=e).astype(jnp.int32)
    return sorted_token, counts


def test_padded_group_layout_invariants():
    from rocket_tpu.ops.gather_gmm import padded_group_layout

    e, tm, nk = 4, 16, 50
    sorted_token, counts = _routing(nk, e)
    row_ids, gsz, padded_pos, m = padded_group_layout(
        counts, sorted_token, tm, nk
    )
    assert m % tm == 0 and int(jnp.sum(gsz)) == m
    assert (np.asarray(gsz) % tm == 0).all()
    # Every sorted row lands at a unique padded position carrying its
    # source-token id.
    pos = np.asarray(padded_pos)
    assert len(set(pos.tolist())) == nk
    np.testing.assert_array_equal(
        np.asarray(row_ids)[pos], np.asarray(sorted_token)
    )


def test_padded_group_layout_empty_expert():
    """A zero-count expert contributes a zero-size padded group — the
    layout and kernel must survive it."""
    from rocket_tpu.ops.gather_gmm import gather_gmm, padded_group_layout

    e, tm, nk = 4, 8, 24
    # Everything routes to experts 0 and 3.
    pair_expert = jnp.asarray(([0] * 11) + ([3] * 13), jnp.int32)
    order = jnp.argsort(pair_expert, stable=True)
    sorted_token = jnp.arange(nk, dtype=jnp.int32)[order]
    counts = jnp.bincount(pair_expert, length=e).astype(jnp.int32)
    row_ids, gsz, padded_pos, m = padded_group_layout(
        counts, sorted_token, tm, nk
    )
    x = jnp.asarray(RNG.normal(size=(nk, 16)).astype(np.float32))
    rhs = jnp.asarray(RNG.normal(size=(e, 16, 128)).astype(np.float32))
    out = gather_gmm(x, rhs, row_ids, gsz, tile_m=tm, tile_n=128,
                     interpret=True)[padded_pos]
    expert_of = np.asarray(pair_expert)[np.argsort(np.asarray(pair_expert),
                                                   kind="stable")]
    want = np.stack([
        np.asarray(x)[int(t)] @ np.asarray(rhs)[int(ex)]
        for t, ex in zip(np.asarray(sorted_token), expert_of)
    ])
    np.testing.assert_allclose(np.asarray(out), want, rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("tile_m,tile_n", [(8, 128), (16, 128), (16, 256)])
def test_gather_gmm_parity(tile_m, tile_n):
    """The in-kernel-routed grouped matmul matches the explicit
    gather + grouped-matmul reference — fwd and grads."""
    from rocket_tpu.nn.moe import _grouped_matmul
    from rocket_tpu.ops.gather_gmm import gather_gmm, padded_group_layout

    n_tok, k, n_out, e = 48, 64, 256, 3
    x = jnp.asarray(RNG.normal(size=(n_tok, k)).astype(np.float32) * 0.2)
    rhs = jnp.asarray(
        RNG.normal(size=(e, k, n_out)).astype(np.float32) * 0.2
    )
    sorted_token, counts = _routing(n_tok, e, key=7)
    row_ids, gsz, padded_pos, _ = padded_group_layout(
        counts, sorted_token, tile_m, n_tok
    )

    def fused(x, rhs):
        return gather_gmm(x, rhs, row_ids, gsz, tile_m=tile_m,
                          tile_n=tile_n, interpret=True)[padded_pos]

    def reference(x, rhs):
        return _grouped_matmul(
            jnp.take(x, row_ids, axis=0), rhs, gsz
        )[padded_pos]

    ok, err = check_parity(
        _value_and_grads(reference, x, rhs),
        _value_and_grads(fused, x, rhs),
        "float32",
    )
    assert ok, (tile_m, tile_n, err)


def test_moe_dropless_fused_impl_parity(monkeypatch):
    """The whole dropless dispatch under impl=fused matches impl=gmm —
    outputs, aux and grads — and actually routes through the kernel."""
    from rocket_tpu.nn.moe import MoE

    moe = MoE(64, 128, 4, top_k=2, dispatch="dropless")
    params = moe.init_params(jax.random.key(0))
    x = jax.random.normal(jax.random.key(1), (2, 16, 64)) * 0.5

    def step(params, x):
        y, aux = moe.apply({"params": params, "state": {}}, x)
        return y

    ref = _value_and_grads(step, params, x)
    assert _pallas_calls(step, params, x) == 0
    monkeypatch.setenv("ROCKET_TPU_MOE_GMM", "fused")
    assert _pallas_calls(step, params, x) == 1
    got = _value_and_grads(step, params, x)
    ok, err = check_parity(ref, got, "float32")
    assert ok, err


def test_moe_dropless_vs_capacity_reference_dropped_token_diff(monkeypatch):
    """The dropped-token diff the dropless variant exists to remove:
    with ample capacity the einsum reference matches the fused dropless
    path; with tight capacity the reference DROPS routed pairs
    (frac_dropped > 0, outputs diverge) while dropless never does."""
    from rocket_tpu.nn.moe import MoE

    dim, hidden, e, k = 16, 32, 4, 2
    x = jax.random.normal(jax.random.key(0), (3, 24, dim))
    params = MoE(dim, hidden, e, top_k=k).init_params(jax.random.key(1))
    monkeypatch.setenv("ROCKET_TPU_MOE_GMM", "fused")
    moe_d = MoE(dim, hidden, e, top_k=k, dispatch="dropless")
    y_d, aux_d = moe_d.apply({"params": params, "state": {}}, x)
    assert float(aux_d["frac_dropped"]) == 0.0

    ample = MoE(dim, hidden, e, top_k=k, capacity_factor=e / k,
                dispatch="einsum")
    y_a, aux_a = ample.apply({"params": params, "state": {}}, x)
    assert float(aux_a["frac_dropped"]) == 0.0
    np.testing.assert_allclose(np.asarray(y_a), np.asarray(y_d), atol=1e-5)

    tight = MoE(dim, hidden, e, top_k=k, capacity_factor=0.4,
                dispatch="einsum")
    y_t, aux_t = tight.apply({"params": params, "state": {}}, x)
    assert float(aux_t["frac_dropped"]) > 0.0
    # The divergence IS the dropped tokens' lost expert contribution.
    assert float(jnp.abs(y_t - y_d).max()) > 1e-3


# -- sched_audit coverage (RKT504 over the fused programs) -------------------


def test_fused_kernels_sched_target_prices_all_three():
    from rocket_tpu.analysis.sched_audit import (
        SCHED_TARGETS,
        run_sched_target,
    )

    report = run_sched_target(SCHED_TARGETS["fused_kernels"])
    names = {fact.name for fact in report.pallas}
    assert {"_twopass_kernel", "_block_kernel",
            "_gather_gmm_kernel"} <= names
    assert report.findings == []
    for fact in report.pallas:
        assert fact.vmem_bytes_est < 16 << 20, fact


def test_pallas_fact_excludes_any_space_operands():
    """An ANY/HBM-resident operand (manually DMA'd, e.g. gather_gmm's
    token array) must not count toward the double-buffered VMEM
    estimate — it would flag every HBM-resident operand as an
    overflow."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    from rocket_tpu.analysis.sched_audit import collect_pallas_facts

    big = 8192

    def kernel(x_ref, o_ref):
        o_ref[...] = jnp.zeros_like(o_ref)

    def step(variables, batch):
        out = pl.pallas_call(
            kernel,
            in_specs=[pl.BlockSpec(memory_space=pltpu.ANY)],
            out_specs=pl.BlockSpec((8, 128), lambda: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
            interpret=True,
        )(batch["x"])
        return variables, out.sum()

    batch = {"x": jax.ShapeDtypeStruct((big, big), jnp.float32)}
    (fact,) = collect_pallas_facts(step, {"params": {}, "state": {}},
                                   batch)
    # Only the (8, 128) out block is double-buffered; the 256 MiB ANY
    # operand is excluded.
    assert fact.vmem_bytes_est == 2 * 8 * 128 * 4
