"""Benchmark: MNIST-MLP training throughput through the full capsule stack.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": "samples/sec/chip", "vs_baseline": N}

Baseline: the same 784-512-256-10 MLP, batch 1024, SGD, trained with
torch-CPU (BASELINE.json configs[0] "single-device CPU ref"), measured on
this host at 35768 samples/sec — see BASELINE.md. ``vs_baseline`` is the
ratio of this framework's per-chip throughput to that number.

Run on whatever ``jax.devices()`` exposes (the driver runs it on one real TPU
chip); all devices are put on a data-parallel mesh axis and throughput is
normalized per chip.
"""

import argparse
import json
import time

import jax
import numpy as np
import optax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.datasets import ArrayDataset
from rocket_tpu.models.mlp import MLP

TORCH_CPU_BASELINE_SAMPLES_PER_SEC = 35768.0


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


class Timer(rt.Capsule):
    """Starts the clock after `warmup` steps (past compile), device-synced."""

    def __init__(self, module, warmup: int):
        super().__init__(priority=50)  # after all work capsules
        self._module = module
        self._warmup = warmup
        self.count = 0
        self.t0 = None

    def launch(self, attrs=None):
        self.count += 1
        self.last_params = self._module.state["params"]
        if self.count == self._warmup:
            jax.block_until_ready(self.last_params)
            self.t0 = time.perf_counter()


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--batch", type=int, default=1024)
    parser.add_argument("--warmup", type=int, default=10)
    parser.add_argument("--steps", type=int, default=60)
    args = parser.parse_args()

    n_dev = len(jax.devices())
    runtime = rt.Runtime(seed=0)

    total = args.batch * (args.warmup + args.steps)
    rng = np.random.default_rng(0)
    data = ArrayDataset(
        rng.normal(size=(total, 784)).astype(np.float32),
        rng.integers(0, 10, size=total).astype(np.int32),
    )

    model = MLP(in_features=784, num_classes=10, hidden=(512, 256))
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.sgd(), learning_rate=0.01)],
    )
    timer = Timer(module, warmup=args.warmup)
    launcher = rt.Launcher(
        [
            rt.Looper(
                [rt.Dataset(data, batch_size=args.batch), module, timer],
                tag="train",
                progress=False,
            )
        ],
        num_epochs=1,
        runtime=runtime,
    )

    launcher.launch()

    jax.block_until_ready(timer.last_params)
    t1 = time.perf_counter()
    elapsed = t1 - timer.t0
    measured_samples = args.batch * args.steps
    per_chip = measured_samples / elapsed / n_dev

    print(
        json.dumps(
            {
                "metric": "mnist_mlp_train_samples_per_sec_per_chip",
                "value": round(per_chip, 1),
                "unit": "samples/sec/chip",
                "vs_baseline": round(per_chip / TORCH_CPU_BASELINE_SAMPLES_PER_SEC, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
