"""Benchmark suite: every BASELINE.json north-star config, one JSON line.

Configs (driver contract: stdout carries exactly ONE JSON line; progress
goes to stderr):

* ``gpt2``       — GPT-2 124M, B=8, T=1024, bf16, flash attention, AdamW
                   (BASELINE.json configs[4], single chip). THE headline
                   metric: tok/sec/chip + MFU.
* ``gpt2_350m``  — GPT-2 medium (d=1024, ~354M params): the wider matmuls
                   fill the MXU better — the framework's best-MFU config.
* ``llama``      — Llama-family 124M-class (RoPE + RMSNorm + SwiGLU +
                   GQA-4): the second model family's throughput.
* ``charlm``     — TinyShakespeare char-transformer, B=128, T=256
                   (configs[2]): tok/sec/chip + MFU.
* ``resnet18``   — CIFAR-10 ResNet-18, B=256 (configs[1]): samples/sec/chip.
* ``resnet50``   — ImageNet-shape ResNet-50, B=128 (configs[3], single
                   chip — the per-chip batch is the measured throughput
                   knee, see bench_resnet50; the DDP scaling half needs
                   real multi-chip hardware): samples/sec/chip + MFU.
* ``mlp``        — MNIST MLP, B=1024 (configs[0], round-1 continuity):
                   samples/sec/chip vs the torch-CPU measurement.

Every config drives the FULL capsule stack (Launcher/Looper/Dataset/Module)
— framework overhead is part of the number. Timing syncs with a real host
fetch: ``jax.block_until_ready`` is a no-op through this environment's
device tunnel, so the timer capsule fetches a device scalar at each window
boundary. The measured steps are split into 3 windows; ``value``/``mfu``
are the ALL-WINDOW MEAN (the honest headline — round-3 verdict weak #5:
a best-window default invited silent best-case comparisons), while
``best_value``/``best_mfu`` carry the fastest window — the chip is shared
and contention varies throughput 2-3x run-to-run, so the best steady-state
window measures the program, the mean measures the neighbours too.

``vs_baseline`` on the headline line is GPT-2 throughput vs the round-1
measurement of this same framework (53.9k tok/s — the reference publishes
no numbers at all, see BASELINE.md), i.e. the round-over-round speedup
(mean-vs-mean, like ``history``).
"""

import argparse
import json
import os
import sys
import time

import jax
import numpy as np
import optax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.datasets import ArrayDataset
from rocket_tpu.data.text import TokenDataset, synthetic_corpus, CharTokenizer
from rocket_tpu.models.mlp import MLP
from rocket_tpu.models.resnet import resnet18
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    next_token_loss,
)

TORCH_CPU_MLP_BASELINE = 35768.0      # samples/sec, measured on this host (r1)
ROUND1_GPT2_TOKS = 53900.0            # tok/sec/chip, judge-measured round 1

def peak_flops():
    """bf16 peak for the local device kind, or None when unknown (MFU is
    then omitted rather than silently computed against the wrong peak)."""
    from rocket_tpu.utils.perf import peak_flops as _peak

    peak = _peak()
    if peak is None:
        log(f"bench: unknown device kind {jax.devices()[0].device_kind!r} — omitting MFU")
    return peak


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def cross_entropy(b):
    return optax.softmax_cross_entropy_with_integer_labels(
        b["logits"], b["label"]
    ).mean()


def _class_dataset(shape, batch, warmup, steps, num_classes=10):
    rng = np.random.default_rng(0)
    total = batch * (warmup + steps)
    return ArrayDataset(
        rng.normal(size=(total, *shape)).astype(np.float32),
        rng.integers(0, num_classes, size=total).astype(np.int32),
    )


class Timer(rt.Capsule):
    """Measures steady-state step time with true device syncs.

    Starts the clock after ``warmup`` steps (past compile), syncing via a
    host fetch of the module's device step counter. The measured steps are
    split into ``windows`` sub-windows with a sync fetch only at each
    boundary — steps inside a window still pipeline — and the caller reads
    the BEST window. The chip here is shared and run-to-run contention
    varies throughput 2-3x; the best steady-state window reflects what the
    hardware+program can do, the mean reflects whoever else was on the chip.
    """

    def __init__(self, module, warmup: int, steps: int, windows: int = 3):
        super().__init__(priority=50)  # after all work capsules
        if warmup < 1:
            # The opening mark fires at measured == 0, i.e. on the warmup-th
            # launch; warmup=0 would silently drop the first window.
            raise ValueError("Timer needs warmup >= 1")
        self._module = module
        self._warmup = warmup
        self.window_steps = max(1, steps // max(1, windows))
        self.count = 0
        self._marks = []

    def _sync_mark(self):
        # device_get, not block_until_ready: through the tunneled
        # runtime, block_until_ready has been observed to return before
        # execution actually retires (a GPT-2 window once timed at an
        # impossible 7x MFU); fetching the counter value is unambiguous.
        int(np.asarray(self._last_step))  # true device sync
        self._marks.append(time.perf_counter())

    def launch(self, attrs=None):
        self.count += 1
        # Keep a handle on the live device step counter: the launcher's
        # destroy pass clears the module before stop() runs.
        self._last_step = self._module.state["step"]
        if self.count == 1:
            self.n_params = sum(
                int(l.size) for l in jax.tree.leaves(self._module.state["params"])
            )
            # Expert-FFN params (leaves under an 'experts' subtree): MoE
            # FLOPs count only the top-k ACTIVE experts per token.
            self.n_expert_params = sum(
                int(leaf.size)
                for path, leaf in jax.tree_util.tree_flatten_with_path(
                    self._module.state["params"]
                )[0]
                if any(
                    getattr(p, "key", getattr(p, "name", None)) == "experts"
                    for p in path
                )
            )
        measured = self.count - self._warmup
        if measured >= 0 and measured % self.window_steps == 0:
            self._sync_mark()

    def stop(self) -> float:
        """Total measured wall time (all complete windows)."""
        return self._marks[-1] - self._marks[0]

    def best_step_time(self) -> float:
        """Seconds/step in the fastest complete window. Marks land only on
        complete window boundaries, so every span here covers exactly
        ``window_steps`` steps."""
        spans = [
            (b - a) / self.window_steps
            for a, b in zip(self._marks, self._marks[1:])
        ]
        return min(spans)

    def mean_step_time(self) -> float:
        """Seconds/step averaged over ALL complete windows — comparable to
        single-window measurements (the round-1 baselines)."""
        return self.stop() / (self.window_steps * (len(self._marks) - 1))


def _train(capsules, runtime, timer):
    launcher = rt.Launcher(
        [rt.Looper(capsules + [timer], tag="train", progress=False)],
        num_epochs=1,
        runtime=runtime,
    )
    launcher.launch()


def bench_mlp(warmup=10, steps=60, batch=1024):
    n_dev = len(jax.devices())
    runtime = rt.Runtime(seed=0)
    data = _class_dataset((784,), batch, warmup, steps)
    model = MLP(in_features=784, num_classes=10, hidden=(512, 256))
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy), rt.Optimizer(optim.sgd(), learning_rate=0.01)],
    )
    timer = Timer(module, warmup, steps)
    _train([rt.Dataset(data, batch_size=batch), module], runtime, timer)
    best_per_chip = batch / timer.best_step_time() / n_dev
    # vs_baseline rides the full-window MEAN — the torch-CPU baseline was
    # measured as a mean, so the ratio must not absorb the best-window pick.
    per_chip = batch / timer.mean_step_time() / n_dev
    return {
        "metric": "mnist_mlp_samples_per_sec_per_chip",
        "value": round(per_chip, 1),
        "unit": "samples/sec/chip",
        "best_value": round(best_per_chip, 1),
        "vs_baseline": round(per_chip / TORCH_CPU_MLP_BASELINE, 3),
    }


def _bench_cnn(model, shape, batch, warmup, steps, metric, gmacs_fwd,
               num_classes):
    """Shared CNN bench body. ``gmacs_fwd``: forward G-MACs per sample
    (1 MAC = 2 FLOPs, matching peak_flops' FMA hardware peak); training
    counts ~3x forward."""
    import jax.numpy as jnp

    n_dev = len(jax.devices())
    # 4 GB cache budget: the ImageNet-shape dataset for a 30-step window
    # split is ~1.3 GB — v5e HBM holds it with room to spare, and keeping
    # the device-resident path is what makes this a compute benchmark
    # (streaming would measure the ~1 GB/s host tunnel instead).
    runtime = rt.Runtime(seed=0, device_cache_bytes=4 << 30)
    data = _class_dataset(shape, batch, warmup, steps, num_classes=num_classes)
    module = rt.Module(
        model,
        capsules=[
            rt.Loss(cross_entropy),
            rt.Optimizer(optim.momentum(beta=0.9), learning_rate=0.1),
        ],
        compute_dtype=jnp.bfloat16,
    )
    timer = Timer(module, warmup, steps)
    _train(
        [
            rt.Dataset(
                data, batch_size=batch, drop_last=True,
                # The model computes bf16; storing the cache at compute
                # precision halves the per-step gather traffic (f32 cache
                # gather measured 4.1 ms/step vs 2.4 bf16 at B=128
                # ImageNet shapes — docs/performance.md).
                cache_dtype=jnp.bfloat16,
            ),
            module,
        ],
        runtime, timer,
    )
    best_per_chip = batch / timer.best_step_time() / n_dev
    per_chip = batch / timer.mean_step_time() / n_dev
    out = {
        "metric": metric,
        "value": round(per_chip, 1),
        "unit": "samples/sec/chip",
        "best_value": round(best_per_chip, 1),
    }
    peak = peak_flops()
    if peak is not None:
        flops_per_sample = 3 * 2 * gmacs_fwd * 1e9
        out["mfu"] = round(per_chip * flops_per_sample / peak, 4)
        out["best_mfu"] = round(best_per_chip * flops_per_sample / peak, 4)
    return out


#: ResNet-18 bench batch — shared with the sched-audit calibration leg
#: so the predicted and the measured step stay the same program.
RESNET18_BATCH = 256


def bench_resnet18(warmup=5, steps=30, batch=RESNET18_BATCH):
    # CIFAR-stem ResNet-18 @32x32: ~0.557 G-MACs forward per sample.
    return _bench_cnn(
        resnet18(num_classes=10, stem="cifar"), (32, 32, 3), batch,
        warmup, steps, "cifar_resnet18_samples_per_sec_per_chip",
        gmacs_fwd=0.557, num_classes=10,
    )


def bench_resnet50(warmup=4, steps=30, batch=128):
    from rocket_tpu.models.resnet import resnet50

    # ResNet-50 @224x224: ~4.1 G-MACs forward per sample. B=128/chip is the
    # measured throughput knee (B=64: 24% MFU bare-loop, B=128: 27%,
    # B=192: 24%); BASELINE configs[3] pins the model, not the per-chip
    # batch.
    return _bench_cnn(
        resnet50(num_classes=1000), (224, 224, 3), batch,
        warmup, steps, "imagenet_resnet50_samples_per_sec_per_chip",
        gmacs_fwd=4.1, num_classes=1000,
    )


def _bench_lm(config, batch, warmup, steps, name, lr=3e-4):
    import jax.numpy as jnp

    n_dev = len(jax.devices())
    runtime = rt.Runtime(seed=0)
    seq = config.max_seq_len
    rng = np.random.default_rng(0)
    tokens = rng.integers(
        0, config.vocab_size, size=seq * (batch * (warmup + steps) + 1)
    ).astype(np.int32)
    data = TokenDataset(tokens, seq_len=seq)
    model = TransformerLM(config)
    module = rt.Module(
        model,
        capsules=[
            rt.Loss(next_token_loss()),
            rt.Optimizer(optim.adamw(), learning_rate=lr),
        ],
        compute_dtype=jnp.bfloat16,
    )
    timer = Timer(module, warmup, steps)
    moe_dropped = {}

    class MoESpy(rt.Capsule):
        """Keeps a handle on the last step's capacity-overflow fraction (a
        device scalar from step_metrics; fetched ONCE after the run —
        never mid-loop)."""

        def __init__(self):
            super().__init__(priority=40)  # after the Timer

        def launch(self, attrs=None):
            if attrs is not None and attrs.step_metrics is not None:
                v = attrs.step_metrics.moe_frac_dropped
                if v is not None:
                    moe_dropped["value"] = v

    extra_capsules = [MoESpy()] if config.num_experts > 0 else []
    _train(
        [rt.Dataset(data, batch_size=batch, drop_last=True), module]
        + extra_capsules,
        runtime, timer,
    )
    best_tok_per_chip = batch * seq / timer.best_step_time() / n_dev
    tok_per_chip = batch * seq / timer.mean_step_time() / n_dev
    # MoE: only the k routed experts' params do FLOPs per token (the
    # dispatch/combine einsum overhead is NOT counted — conservative MFU).
    active_params = timer.n_params
    if config.num_experts > 0 and timer.n_expert_params:
        active_params -= timer.n_expert_params * (
            1 - config.expert_top_k / config.num_experts
        )
    flops_per_tok = 6 * active_params + 12 * config.num_layers * seq * config.dim
    out = {
        "metric": f"{name}_tok_per_sec_per_chip",
        "value": round(tok_per_chip, 1),
        "unit": "tok/sec/chip",
        "best_value": round(best_tok_per_chip, 1),
    }
    peak = peak_flops()
    if peak is not None:
        # "mfu" follows "value" (all-window mean — the round-over-round
        # comparable); "best_mfu" tracks the fastest window.
        out["mfu"] = round(tok_per_chip * flops_per_tok / peak, 4)
        out["best_mfu"] = round(best_tok_per_chip * flops_per_tok / peak, 4)
    if "value" in moe_dropped:
        # Capacity waste tracked round-over-round (round-4 verdict ask #3);
        # identically 0 under the dropless dispatch.
        out["frac_dropped"] = round(float(np.asarray(moe_dropped["value"])), 4)
    return out


#: charlm bench batch — shared with the sched-audit calibration leg.
CHARLM_BATCH = 128


def charlm_config():
    """The charlm bench model config, built ONCE — the sched-audit
    calibration leg predicts exactly the config this bench measures."""
    tok = CharTokenizer(synthetic_corpus(10_000))
    config = TransformerConfig.char_lm(
        vocab_size=tok.vocab_size, max_seq_len=256
    )
    config.dropout = 0.0
    return config


def bench_charlm(warmup=5, steps=40):
    return _bench_lm(charlm_config(), batch=CHARLM_BATCH, warmup=warmup,
                     steps=steps, name="charlm")


def bench_gpt2(warmup=5, steps=30):
    config = TransformerConfig.gpt2_124m()
    config.dropout = 0.0
    out = _bench_lm(config, batch=8, warmup=warmup, steps=steps, name="gpt2_124m")
    # Mean-vs-mean: the round-1 judge measurement was a single-window mean,
    # so the ratio must not absorb the best-window pick.
    out["vs_baseline"] = round(out["value"] / ROUND1_GPT2_TOKS, 3)
    return out


def bench_gpt2_350m(warmup=4, steps=15):
    config = TransformerConfig.gpt2_350m()
    config.dropout = 0.0
    return _bench_lm(config, batch=8, warmup=warmup, steps=steps, name="gpt2_350m")


def bench_llama(warmup=4, steps=15):
    # Second model family: RoPE + RMSNorm + SwiGLU + GQA (124M-class dims).
    config = TransformerConfig.llama_style()
    return _bench_lm(config, batch=8, warmup=warmup, steps=steps, name="llama_style")


def bench_longctx(warmup=3, steps=12):
    """Long-context single-chip: Llama-style 124M-class at T=4096 (B=2 —
    same tokens/step as the T=1024 config). Exercises the flash kernel's
    long-sequence regime (nk=8 kv blocks, f32 dq partials); the
    sequence-PARALLEL path (ring attention over a 'seq' axis) is
    validated by dryrun_multichip — one physical chip here."""
    config = TransformerConfig.llama_style(max_seq_len=4096)
    return _bench_lm(config, batch=2, warmup=warmup, steps=steps,
                     name="llama_t4096")


def bench_moe(warmup=4, steps=15):
    """Single-chip MoE LM (GPT-2-small dims, 4 experts, top-2): routed-FFN
    throughput + MFU over ACTIVE params (round-3 verdict ask #4 — MoE was
    correctness-proven but perf-unmeasured)."""
    config = TransformerConfig.gpt2_124m()
    config.dropout = 0.0
    config.num_experts = 4
    config.expert_top_k = 2
    config.expert_capacity_factor = 1.25
    return _bench_lm(config, batch=8, warmup=warmup, steps=steps, name="moe_gpt2_e4")


def bench_pipeline(warmup=3, steps=12):
    """GPipe schedule sanity wall-clock on a VIRTUAL 4-stage CPU mesh (one
    physical chip here — this measures that the compiled M+P-1-tick
    schedule executes and stays within a sane multiple of the unpipelined
    scan on the SAME virtual mesh; it is NOT chip performance)."""
    import subprocess

    code = r"""
import json, time, os, sys
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, %r)
import jax.numpy as jnp
import numpy as np
from rocket_tpu.models.transformer import TransformerConfig, TransformerLM, next_token_loss
from rocket_tpu.runtime.context import Runtime
from rocket_tpu.parallel.sharding import pipeline_rules
import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import TokenDataset

runtime = Runtime(mesh_shape={"pipe": 4}, devices=jax.devices()[:4], seed=0)
config = TransformerConfig(
    vocab_size=256, max_seq_len=128, dim=128, num_layers=4, num_heads=4,
    dropout=0.0, scan_layers=True, pipeline_axis="pipe",
    pipeline_microbatches=4,
)
rng = np.random.default_rng(0)
warmup, steps = %d, %d
data = TokenDataset(rng.integers(0, 256, size=128 * (warmup + steps + 1) * 8).astype(np.int32), seq_len=128)
module = rt.Module(
    TransformerLM(config),
    capsules=[rt.Loss(next_token_loss()), rt.Optimizer(optim.adamw(), learning_rate=1e-3)],
    param_sharding=pipeline_rules(),
)
marks = []
class Timer(rt.Capsule):
    def __init__(self):
        super().__init__(priority=50)
        self.count = 0
    def launch(self, attrs=None):
        self.count += 1
        if self.count >= warmup:
            float(np.asarray(attrs.step_metrics.loss))
            marks.append(time.perf_counter())
rt.Launcher(
    [rt.Looper([rt.Dataset(data, batch_size=8, drop_last=True), module, Timer()],
               tag="train", progress=False)],
    num_epochs=1, runtime=runtime,
).launch()
dt = (marks[-1] - marks[0]) / (len(marks) - 1)
print(json.dumps({"steps_per_sec": 1.0 / dt}))
"""
    repo = os.path.dirname(os.path.abspath(__file__))
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        env.get("XLA_FLAGS", "").replace(
            "--xla_force_host_platform_device_count=8", ""
        ).strip() + " --xla_force_host_platform_device_count=4"
    ).strip()
    proc = subprocess.run(
        [sys.executable, "-c", code % (repo, warmup, steps)],
        env=env, capture_output=True, text=True, timeout=900,
    )
    if proc.returncode != 0:
        raise RuntimeError(
            f"pipeline sanity subprocess failed: {proc.stderr[-500:]}"
        )
    sps = json.loads(proc.stdout.strip().splitlines()[-1])["steps_per_sec"]
    return {
        "metric": "pipeline_gpipe_virtual4_steps_per_sec",
        "value": round(sps, 3),
        "unit": "steps/sec (virtual 4-stage CPU mesh sanity, not chip perf)",
    }


BENCHES = {
    "gpt2": bench_gpt2,
    "gpt2_350m": bench_gpt2_350m,
    "llama": bench_llama,
    "moe": bench_moe,
    "charlm": bench_charlm,
    "resnet18": bench_resnet18,
    "resnet50": bench_resnet50,
    "mlp": bench_mlp,
    "pipeline": bench_pipeline,
    # Last on purpose: the soft time budget must never starve the configs
    # above, which carry round-over-round HISTORY continuity.
    "longctx": bench_longctx,
}


def _require_live_backend(headline_metric: str, timeout_s: float = 120.0) -> None:
    """Fail fast (one JSON error line) when the device backend is
    unreachable — the tunneled TPU goes down for hours at a time, and a
    hung jax.devices() would otherwise stall the whole bench run."""
    import threading

    ok = threading.Event()

    def probe():
        try:
            jax.devices()
            ok.set()
        except Exception:
            pass

    thread = threading.Thread(target=probe, daemon=True)
    thread.start()
    thread.join(timeout_s)
    if not ok.is_set():
        print(
            json.dumps(
                {
                    "metric": headline_metric,
                    "error": f"device backend unreachable after {timeout_s:.0f}s",
                }
            ),
            flush=True,
        )
        import os

        os._exit(1)


#: Headline metric name per config (error reporting when the backend is down).
METRIC_NAMES = {
    "gpt2": "gpt2_124m_tok_per_sec_per_chip",
    "gpt2_350m": "gpt2_350m_tok_per_sec_per_chip",
    "llama": "llama_style_tok_per_sec_per_chip",
    "longctx": "llama_t4096_tok_per_sec_per_chip",
    "moe": "moe_gpt2_e4_tok_per_sec_per_chip",
    "charlm": "charlm_tok_per_sec_per_chip",
    "resnet18": "cifar_resnet18_samples_per_sec_per_chip",
    "resnet50": "imagenet_resnet50_samples_per_sec_per_chip",
    "mlp": "mnist_mlp_samples_per_sec_per_chip",
    "pipeline": "pipeline_gpipe_virtual4_steps_per_sec",
}

#: Round-over-round history: regressions must be visible at a glance
#: (round-3 verdict ask #8). r01 entries are single-window means (that was
#: the round-1 methodology); r02+ entries are the all-window means recorded
#: in BENCH_r{N}.json (field ``mean_value`` through r03, ``value`` from r04
#: on — same quantity, renamed per round-3 verdict ask #6). ``now`` is this
#: run's ``value``; never compare best windows across rounds.
HISTORY = {
    # r04 values recovered from BENCH_r04.json's raw tail (the parsed
    # field is null there — the line overflowed the driver's 2000-byte
    # capture; fixed in round 5 by the compact-line + BENCH_DETAIL.json
    # split below). The gpt2 r04 entry matches the committed SURVEY.md
    # round-4 table (125.4k mean).
    "gpt2": {"r01": 53900.0, "r02": 105611.2, "r03": 126048.7,
             "r04": 125396.4},
    "gpt2_350m": {"r02": 39927.5, "r03": 49765.1, "r04": 48617.4},
    "llama": {"r02": 80755.3, "r03": 86502.8, "r04": 94499.4},
    "longctx": {"r04": 65290.7},
    "moe": {"r03": 65633.9, "r04": 65807.3},
    "charlm": {"r02": 821903.2, "r03": 1506723.2, "r04": 1454929.8},
    "resnet18": {"r02": 13190.4, "r03": 13902.4, "r04": 15334.0},
    "resnet50": {"r02": 1119.0, "r03": 1989.2, "r04": 2084.1},
    "mlp": {"r01": 363649.3, "r02": 135668.8, "r03": 177148.8,
            "r04": 155305.2},
}


#: Hard cap on the emitted stdout line. The driver records only the last
#: 2,000 bytes of output — BENCH_r04.json came back ``parsed: null``
#: because the old monolithic line (headline + full per-config ``extra``)
#: outgrew that window and the capture started mid-stream. The headline
#: is now emitted compact and SELF-CONTAINED; everything else goes to
#: ``BENCH_DETAIL.json`` in the repo. 1,500 leaves headroom for any stray
#: trailing output sharing the tail window.
MAX_LINE_BYTES = 1500

DETAIL_PATH = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_DETAIL.json"
)

VALUE_POLICY = (
    "value/mfu=all-window mean; best_value/best_mfu=best of 3 windows; "
    "vs_baseline and history use means"
)


def _pick_headline(results):
    ok = {n: r for n, r in results.items() if "error" not in r}
    return ok.get("gpt2") or next(iter(ok.values()), None) \
        or next(iter(results.values()))


#: Budget-file directory the static SPMD auditor maintains
#: (``python -m rocket_tpu.analysis shard --update-budgets``).
BUDGETS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tests", "fixtures", "budgets",
)


def _budget_summary(budgets_dir, keys_attr, source):
    """Shared reader for the committed audit-budget records: per-target
    gated keys (named by ``keys_attr`` on the budgets module, resolved
    inside the guard) plus a worst-case (max) headline per key. None
    when no budgets are committed; never raises — BENCH emission must
    survive a missing or corrupt record. (The audits themselves run in
    CI — re-running them here would duplicate the gate, not the
    measurement.)"""
    try:
        from rocket_tpu.analysis import budgets as budgets_mod
        keys = getattr(budgets_mod, keys_attr)
        load_budget = budgets_mod.load_budget
        names = sorted(
            os.path.splitext(f)[0] for f in os.listdir(budgets_dir)
            if f.endswith(".json")
        )
        targets = {}
        for name in names:
            record = load_budget(budgets_dir, name)
            if record is None:
                continue
            targets[name] = {key: record.get(key) for key in keys}
        if not targets:
            return None
        summary = {"targets": targets, "source": source}
        for key in keys:
            summary[key] = max(t[key] or 0 for t in targets.values())
        return summary
    except Exception:  # noqa: BLE001 — emission must never die on this
        return None


def shard_audit_summary(budgets_dir=BUDGETS_DIR):
    """The audited per-device HBM estimate and per-step collective-bytes
    totals for the repo's canonical sharded configs, from the records
    the SPMD self-gate verifies every CI run."""
    return _budget_summary(
        budgets_dir, "GATED_KEYS", "tests/fixtures/budgets"
    )


#: Numerics-budget directory the precision auditor maintains
#: (``python -m rocket_tpu.analysis prec --update-budgets``).
PREC_BUDGETS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tests", "fixtures", "budgets", "prec",
)


def prec_audit_summary(budgets_dir=PREC_BUDGETS_DIR):
    """The audited mixed-precision numbers (fp32-bytes fraction of the
    traced step's values, widen/narrow cast counts — worst across
    targets) from the records the precision self-gate verifies every CI
    run."""
    return _budget_summary(
        budgets_dir, "PREC_GATED_KEYS", "tests/fixtures/budgets/prec"
    )


#: Schedule-budget directory the roofline auditor maintains
#: (``python -m rocket_tpu.analysis sched --update-budgets``).
SCHED_BUDGETS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tests", "fixtures", "budgets", "sched",
)

#: Configs the sched calibration leg re-predicts: name -> builder() ->
#: (step_fn, variables, batch, donate, units_per_step). The builders
#: derive the model config and batch from the SAME definitions the
#: bench functions measure (charlm_config/CHARLM_BATCH,
#: RESNET18_BATCH), so a bench-config edit cannot silently desync the
#: calibration. Only configs whose measured record exists in this run's
#: results are predicted (each costs one AOT compile).
def _calib_charlm():
    from rocket_tpu.analysis.shard_audit import _lm_parts

    config = charlm_config()
    step_fn, variables, batch, _rules, donate = _lm_parts(
        None, config=config, batch_size=CHARLM_BATCH
    )
    return step_fn, variables, batch, donate, \
        CHARLM_BATCH * config.max_seq_len  # tokens/step


def _calib_resnet18():
    from rocket_tpu.analysis.sched_audit import _resnet_parts

    step_fn, variables, batch, _rules, donate = _resnet_parts(
        batch_size=RESNET18_BATCH
    )
    return step_fn, variables, batch, donate, RESNET18_BATCH  # samples


_SCHED_CALIBRATION = {
    "charlm": _calib_charlm,
    "resnet18": _calib_resnet18,
}


def sched_audit_summary(results=None, budgets_dir=SCHED_BUDGETS_DIR):
    """Predicted step-time attribution + predicted-vs-measured
    calibration for BENCH_DETAIL.json.

    Two halves, both best-effort (None/partial on any failure — emission
    must never die on the audits):

    * the committed schedule-budget records (the numbers the sched
      self-gate verifies every CI run): per-target predicted step time,
      exposed-communication time, overlap fraction and the
      compute/memory/comm attribution;
    * a calibration leg re-predicting the step time of measured bench
      configs (``_SCHED_CALIBRATION``) with the same roofline model, so
      the model/reality drift is itself a tracked number.
      ``calibration_error`` is (predicted - measured) / measured;
      ``device_matched`` is False when the bench device's kind is not in
      the peak table (the prediction then prices the reference kind and
      the error mostly measures that mismatch — e.g. the CPU-only CI
      container). Known structural drift: LM configs run the pallas
      flash kernels on hardware while the fake-mesh compile takes the
      XLA attention path, so conv configs calibrate much tighter.
    """
    out = {}
    try:
        from rocket_tpu.analysis import budgets as budgets_mod

        names = sorted(
            os.path.splitext(f)[0] for f in os.listdir(budgets_dir)
            if f.endswith(".json")
        )
        targets = {}
        worst_step = worst_exposed = 0.0
        for name in names:
            record = budgets_mod.load_budget(budgets_dir, name)
            if record is None:
                continue
            targets[name] = {
                key: record.get(key)
                for key in ("predicted_step_time_us", "exposed_comm_us",
                            "overlap_fraction", "predicted_mfu",
                            "fractions", "bound")
            }
            worst_step = max(worst_step,
                             record.get("predicted_step_time_us") or 0)
            worst_exposed = max(worst_exposed,
                                record.get("exposed_comm_us") or 0)
        if targets:
            out = {
                "targets": targets,
                "predicted_step_time_us": worst_step,
                "exposed_comm_us": worst_exposed,
                "source": "tests/fixtures/budgets/sched",
            }
    except Exception:  # noqa: BLE001 — emission must never die on this
        pass
    try:
        calibration = _sched_calibration(results or {})
        if calibration:
            out["calibration"] = calibration
    except Exception as exc:  # noqa: BLE001
        log(f"bench: sched calibration failed: {exc!r}")
    return out or None


def _sched_calibration(results):
    from rocket_tpu.analysis.sched_audit import (
        DEFAULT_DEVICE_KIND,
        audit_schedule,
    )
    from rocket_tpu.utils.perf import device_spec

    kind = jax.devices()[0].device_kind
    spec = device_spec(kind)
    priced_kind = spec.kind if spec is not None else DEFAULT_DEVICE_KIND
    entries = {}
    for name, build in _SCHED_CALIBRATION.items():
        record = results.get(name) or {}
        value = record.get("value")
        if not value or "error" in record:
            continue
        step_fn, variables, batch, donate, units_per_step = build()
        report = audit_schedule(
            step_fn, variables, batch, mesh_shape={"data": 1},
            device_kind=priced_kind, donate_argnums=donate,
            label=f"calib:{name}",
        )
        predicted_us = report.record.get("predicted_step_time_us")
        if not predicted_us:
            continue
        # value is per-chip; bench configs above are single-chip runs,
        # so units/step / value is the measured step time.
        measured_us = units_per_step / value * 1e6
        entries[name] = {
            "predicted_step_time_us": predicted_us,
            "measured_step_time_us": round(measured_us, 3),
            "calibration_error": round(
                (predicted_us - measured_us) / measured_us, 4
            ),
            "predicted_mfu": report.record.get("predicted_mfu"),
            "overlap_fraction": report.record.get("overlap_fraction"),
            "priced_for": priced_kind,
            "device_matched": spec is not None,
        }
    return entries


#: Calibration-budget directory the measured-vs-predicted auditor
#: maintains (``python -m rocket_tpu.analysis calib --update-budgets``).
CALIB_BUDGETS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tests", "fixtures", "budgets", "calib",
)


def calib_summary(budgets_dir=CALIB_BUDGETS_DIR, live=True):
    """Measured-vs-predicted calibration record for BENCH_DETAIL.json
    (``rocket_tpu.analysis.calib`` / ``rocket_tpu.obs.prof``).

    Two halves, both best-effort:

    * the committed calibration budgets (the numbers the calib gate
      verifies every CI run): per-target absolute calibration error +
      unjoined measured fraction;
    * a ``live`` capture->parse->reconcile leg re-running the
      gpt2_sentinel target on THIS machine — a device trace of the real
      compiled step, bucketed per HLO op and joined against the priced
      DAG, so the record carries the calibration error measured on this
      run's hardware (the first real-TPU bench run turns
      ``device_matched`` True and the error becomes a model-quality
      number instead of a device-mismatch one).
    """
    out = _budget_summary(
        budgets_dir, "CALIB_GATED_KEYS", "tests/fixtures/budgets/calib"
    ) or {}
    if live:
        try:
            from rocket_tpu.analysis.calib import (
                CALIB_TARGETS,
                run_calib_target,
            )

            report = run_calib_target(CALIB_TARGETS["gpt2_sentinel"])
            if report.record:
                keys = (
                    "n_steps", "measured_step_us", "predicted_step_us",
                    "calib_error", "abs_calib_error", "join_coverage",
                    "measured_exposed_comm_us",
                    "predicted_exposed_comm_us", "measured_mfu",
                    "predicted_mfu", "device_kind_measured", "priced_for",
                    "device_matched",
                )
                out["live"] = {"gpt2_sentinel": {
                    k: report.record.get(k) for k in keys
                }}
        except Exception as exc:  # noqa: BLE001 — emission must survive
            log(f"bench: calib live capture failed: {exc!r}")
    return out or None


#: Tuned-kernel config tables the offline autotuner maintains
#: (``python -m rocket_tpu.tune --update-table``).
TUNE_CONFIGS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "rocket_tpu", "tune", "configs",
)


def tune_summary(configs_dir=TUNE_CONFIGS_DIR):
    """Tuned-vs-default kernel config record for BENCH_DETAIL.json.

    Per tunable kernel: the checked-in table's entries — each carries
    its (device kind, shape bucket, dtype) key and the tuner-measured
    ``speedup``/``tuned_us``/``default_us`` — so tuned-vs-default
    speedup is tracked per kernel per device kind round-over-round,
    plus ``structural_wins`` (ISSUE 14): every entry whose winning
    config pins a STRUCTURAL variant (``impl``/``schedule``/
    ``epilogue``) away from the reference implementation, with the
    variant name and the measured speedup vs ``impl=reference`` — the
    generate-and-verify search's soft-spot scoreboard, carried across
    probe-less runs like the rest of the record. An empty table
    (n_entries 0) means the search found no win for that kernel yet and
    every call runs the hand-picked default. ``device_kind`` is THIS
    run's device, so the record says whether the measured throughput
    above could have hit the table at all. Best effort: None on any
    failure — emission must never die on tuning."""
    try:
        from rocket_tpu import tune

        summary = tune.tables_summary(configs_dir)
        if summary is None:
            return None
        summary["device_kind"] = jax.devices()[0].device_kind
        summary["table_device_kinds"] = sorted({
            entry.get("device_kind")
            for kernel in summary["kernels"].values()
            for entry in kernel["entries"]
            if entry.get("device_kind")
        })
        return summary
    except Exception as exc:  # noqa: BLE001 — best-effort, like the audits
        log(f"bench: tune_summary failed: {exc!r}")
        return None


def _reset_tune_provenance():
    """Best-effort: clear the tune lookup log before a config runs."""
    try:
        from rocket_tpu import tune

        tune.reset_lookup_log()
    except Exception:  # noqa: BLE001
        pass


def _tune_provenance():
    """The deduplicated kernel-config lookups the config just traced
    (table hit vs default fallback + the resolved entry key), or None."""
    try:
        from rocket_tpu import tune

        return tune.lookup_log_summary() or None
    except Exception:  # noqa: BLE001
        return None


#: Serving-budget directory the serve auditor maintains
#: (``python -m rocket_tpu.analysis serve --update-budgets``).
#: Peak-HBM budget directory the memory auditor maintains
#: (``python -m rocket_tpu.analysis mem --update-budgets``).
MEM_BUDGETS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tests", "fixtures", "budgets", "mem",
)


def mem_audit_summary(budgets_dir=MEM_BUDGETS_DIR):
    """The audited per-device peak-HBM prediction and saved-activation
    bytes for the repo's canonical train/eval configs, from the records
    the memory self-gate verifies every CI run."""
    return _budget_summary(
        budgets_dir, "MEM_GATED_KEYS", "tests/fixtures/budgets/mem"
    )


#: Determinism-budget directory the repro auditor maintains
#: (``python -m rocket_tpu.analysis repro --update-budgets``).
REPRO_BUDGETS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tests", "fixtures", "budgets", "repro",
)

#: Crash-consistency coverage-budget directory the fault auditor
#: maintains (``python -m rocket_tpu.analysis fault --update-budgets``).
FAULT_BUDGETS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tests", "fixtures", "budgets", "fault",
)


def fault_audit_summary(budgets_dir=FAULT_BUDGETS_DIR):
    """The audited crash-consistency coverage record — crash points
    enumerated across the three checkpoint save paths, supervisor
    states explored by the model check, signal handlers checked — from
    the records the fault self-gate verifies every CI run. Coverage
    fingerprints are identities (any drift fails CI), so this reads
    the records directly rather than riding :func:`_budget_summary`'s
    numeric-max headline."""
    try:
        from rocket_tpu.analysis import budgets as budgets_mod
        keys = budgets_mod.FAULT_GATED_KEYS
        names = sorted(
            os.path.splitext(f)[0] for f in os.listdir(budgets_dir)
            if f.endswith(".json")
        )
        targets = {}
        for name in names:
            record = budgets_mod.load_budget(budgets_dir, name)
            if record is None:
                continue
            targets[name] = {
                key: record.get(key) for key in keys
                if record.get(key) is not None
            }
        if not targets:
            return None
        return {
            "targets": targets,
            "source": "tests/fixtures/budgets/fault",
            "crash_points": max(
                t.get("crash_points") or 0 for t in targets.values()
            ),
            "states_explored": max(
                t.get("states_explored") or 0 for t in targets.values()
            ),
            "handlers_checked": max(
                t.get("handlers_checked") or 0 for t in targets.values()
            ),
        }
    except Exception:  # noqa: BLE001 — emission must never die on this
        return None


def repro_audit_summary(budgets_dir=REPRO_BUDGETS_DIR):
    """The audited determinism record per canonical target — the
    program fingerprint (identity-gated: CI fails on ANY
    drift) plus the RNG-discipline counters — from the records the
    repro self-gate verifies every CI run. Fingerprints are identities,
    not magnitudes, so this cannot ride :func:`_budget_summary` (its
    per-key numeric max would choke on the strings); the headline is
    the worst random-consumer count and the fingerprinted-target tally."""
    try:
        from rocket_tpu.analysis import budgets as budgets_mod
        keys = budgets_mod.REPRO_GATED_KEYS
        names = sorted(
            os.path.splitext(f)[0] for f in os.listdir(budgets_dir)
            if f.endswith(".json")
        )
        targets = {}
        for name in names:
            record = budgets_mod.load_budget(budgets_dir, name)
            if record is None:
                continue
            targets[name] = {key: record.get(key) for key in keys}
        if not targets:
            return None
        return {
            "targets": targets,
            "source": "tests/fixtures/budgets/repro",
            "random_consumers": max(
                t.get("random_consumers") or 0 for t in targets.values()
            ),
            "fingerprinted_targets": sum(
                1 for t in targets.values() if t.get("program_fingerprint")
            ),
        }
    except Exception:  # noqa: BLE001 — emission must never die on this
        return None


SERVE_BUDGETS_DIR = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "tests", "fixtures", "budgets", "serve",
)


def serve_audit_summary(serve=None, budgets_dir=SERVE_BUDGETS_DIR):
    """Predicted serving latency/HBM + predicted-vs-measured calibration
    for BENCH_DETAIL.json.

    Two halves, both best-effort (None/partial on any failure):

    * the committed serving-budget records (the numbers the serve
      self-gate verifies every CI run): per-target predicted ITL/TTFT,
      the analytic floor, the overfetch ratio and the engine HBM
      footprint;
    * a calibration leg re-predicting the ``charlm`` audit target —
      configured byte-identically to :func:`serve_summary`'s engine —
      priced for THIS run's device kind, against the serve record this
      run just measured. ``itl_calibration_error`` is
      (predicted - measured_p50) / measured_p50, same convention as
      sched_audit's calibration; ``device_matched`` False means the
      bench device's kind is absent from the peak table and the error
      mostly measures that mismatch (e.g. the CPU-only CI container).
    """
    out = {}
    try:
        from rocket_tpu.analysis import budgets as budgets_mod

        names = sorted(
            os.path.splitext(f)[0] for f in os.listdir(budgets_dir)
            if f.endswith(".json")
        )
        targets = {}
        worst_itl = worst_ttft = worst_hbm = 0.0
        for name in names:
            record = budgets_mod.load_budget(budgets_dir, name)
            if record is None:
                continue
            targets[name] = {
                key: record.get(key)
                for key in ("predicted_itl_us", "predicted_ttft_us",
                            "itl_floor_us", "overfetch_ratio",
                            "hbm_total_bytes", "host_bytes_per_wave",
                            "host_bytes_per_dispatch",
                            "byte_model", "waves_per_dispatch",
                            "device_kind")
            }
            worst_itl = max(worst_itl, record.get("predicted_itl_us") or 0)
            worst_ttft = max(worst_ttft,
                             record.get("predicted_ttft_us") or 0)
            worst_hbm = max(worst_hbm, record.get("hbm_total_bytes") or 0)
        if targets:
            out = {
                "targets": targets,
                "predicted_itl_us": worst_itl,
                "predicted_ttft_us": worst_ttft,
                "hbm_total_bytes": int(worst_hbm),
                "source": "tests/fixtures/budgets/serve",
            }
    except Exception:  # noqa: BLE001 — emission must never die on this
        pass
    try:
        calibration = _serve_calibration(serve)
        if calibration:
            out["calibration"] = calibration
    except Exception as exc:  # noqa: BLE001
        log(f"bench: serve calibration failed: {exc!r}")
    return out or None


def _serve_calibration(serve):
    """Re-predict the measured serve engine's ITL/TTFT with the static
    roofline, priced for this run's device kind."""
    if not serve:
        return None
    measured_itl_ms = (serve.get("itl_ms") or {}).get("p50")
    measured_ttft_ms = (serve.get("ttft_ms") or {}).get("p50")
    if not measured_itl_ms:
        return None
    from rocket_tpu.analysis.sched_audit import DEFAULT_DEVICE_KIND
    from rocket_tpu.analysis.serve_audit import (
        SERVE_TARGETS,
        audit_serving,
    )
    from rocket_tpu.utils.perf import device_spec

    kind = jax.devices()[0].device_kind
    spec = device_spec(kind)
    priced_kind = spec.kind if spec is not None else DEFAULT_DEVICE_KIND
    target = SERVE_TARGETS["charlm"]
    model, serve_cfg = target.build()
    report = audit_serving(
        model, serve_cfg, device_kind=priced_kind,
        ref_prompt_len=target.ref_prompt_len, label="calib:serve",
    )
    predicted_itl = report.record.get("predicted_itl_us")
    if not predicted_itl:
        return None
    measured_itl_us = measured_itl_ms * 1e3
    entry = {
        "predicted_itl_us": predicted_itl,
        "measured_itl_us": round(measured_itl_us, 3),
        "itl_calibration_error": round(
            (predicted_itl - measured_itl_us) / measured_itl_us, 4
        ),
        "priced_for": priced_kind,
        "device_matched": spec is not None,
    }
    predicted_ttft = report.record.get("predicted_ttft_us")
    if predicted_ttft and measured_ttft_ms:
        measured_ttft_us = measured_ttft_ms * 1e3
        entry["predicted_ttft_us"] = predicted_ttft
        entry["measured_ttft_us"] = round(measured_ttft_us, 3)
        entry["ttft_calibration_error"] = round(
            (predicted_ttft - measured_ttft_us) / measured_ttft_us, 4
        )
    return entry


#: Where a telemetry-enabled bench run's record lands: bench trees carry
#: no Tracker, so Runtime.end_training falls back to
#: <project_dir>/runs/telemetry with project_dir "." — i.e. relative to
#: the CWD bench ran from, not to this file. The repo-rooted path is the
#: second candidate for the usual run-from-repo-root case.
TELEMETRY_CANDIDATES = (
    os.path.join("runs", "telemetry", "telemetry.json"),
    os.path.join(os.path.dirname(os.path.abspath(__file__)),
                 "runs", "telemetry", "telemetry.json"),
)

#: Freshness fence: only a telemetry.json written by THIS process run may
#: enter BENCH_DETAIL.json — a leftover record from an earlier
#: telemetry-enabled run must not masquerade as this run's goodput.
_PROCESS_START = time.time()


def telemetry_summary(path=None):
    """Goodput + key run metrics from this run's telemetry record
    (``ROCKET_TPU_TELEMETRY=1 python bench.py ...``; successive configs
    overwrite, so this records the final config's phases). None when
    telemetry was off, the record predates this process (stale file from
    an earlier run), or it is unreadable — emission must never die on
    observability."""
    try:
        if path is None:
            path = next(
                (p for p in TELEMETRY_CANDIDATES
                 if os.path.exists(p)
                 and os.path.getmtime(p) >= _PROCESS_START),
                None,
            )
            if path is None:
                return None
        with open(path) as f:
            record = json.load(f)
        goodput = record["goodput"]
        metrics = record.get("metrics", {})
        out = {
            "goodput_fraction": goodput.get("goodput_fraction"),
            "total_wall_s": goodput.get("total_wall_s"),
            "fractions": goodput.get("fractions"),
            "source": os.path.relpath(path, os.path.dirname(DETAIL_PATH)),
        }
        gauges = metrics.get("gauges", {})
        for key in ("perf/steps_per_sec", "perf/mfu",
                    "hbm/peak_bytes_in_use_max"):
            if key in gauges:
                out[key] = gauges[key]
        stalls = record.get("watchdog", {}).get("stalls")
        if stalls:
            out["watchdog_stalls"] = stalls
        return out
    except Exception:  # noqa: BLE001 — best-effort, like the audit summaries
        return None


def health_summary(warmup=10, steps=60, batch=1024):
    """Sentinel overhead + anomaly accounting for BENCH_DETAIL.json.

    The MLP config is timed twice — health sentinels OFF, then ON with
    the gated ``skip_step`` action (the most expensive sentinel path:
    per-branch finite checks, norms, the on-device EMA and the lax.cond
    update gate, plus the lagged explicit host fetch). ``overhead_frac``
    is the steps/sec cost of turning sentinels on, best-of-3-windows on
    both sides so shared-chip contention noise largely cancels. Telemetry
    stays OFF in both probes so the probe cannot masquerade as the main
    run's telemetry record. Best effort: None on any failure — emission
    must never die on observability."""
    try:
        sps = {}
        stats = None
        for mode in (False, True):
            runtime = rt.Runtime(
                seed=0, health=mode, anomaly_action="skip_step",
                telemetry=False,
            )
            data = _class_dataset((784,), batch, warmup, steps)
            model = MLP(in_features=784, num_classes=10, hidden=(512, 256))
            module = rt.Module(
                model,
                capsules=[rt.Loss(cross_entropy),
                          rt.Optimizer(optim.sgd(), learning_rate=0.01)],
            )
            timer = Timer(module, warmup, steps)
            _train([rt.Dataset(data, batch_size=batch), module], runtime, timer)
            sps[mode] = 1.0 / timer.best_step_time()
            if mode:
                stats = runtime.health.summary()
        overhead = (sps[False] - sps[True]) / sps[False]
        return {
            "steps_per_sec_baseline": round(sps[False], 2),
            "steps_per_sec_with_sentinels": round(sps[True], 2),
            "overhead_frac": round(overhead, 4),
            "action": stats["action"],
            "anomalies": stats["anomalies"],
            "skipped_steps": stats["skipped_steps"],
            "config": "mlp",
        }
    except Exception as exc:  # noqa: BLE001 — best-effort, like the audits
        log(f"bench: health_summary failed: {exc!r}")
        return None


#: Targets the overlap on/off probe re-audits (the TP/FSDP train
#: targets plus the TP eval step — the paths the overlapped collectives
#: rewire).
OVERLAP_PROBE_TARGETS = ("tp_1x8", "tp_2x4", "fsdp_1x8", "tp_2x4_eval")


def overlap_summary(targets=OVERLAP_PROBE_TARGETS):
    """Overlap-on/off diff of audited collective bytes + simulated
    exposed-communication time per TP/FSDP target, for
    BENCH_DETAIL.json.

    Rebuilds each audit target twice — ``ROCKET_TPU_OVERLAP=1`` (the
    ring/bulk collective-matmul + bucketed-grad paths) and ``=0`` (the
    plain GSPMD program) — and re-runs the SPMD byte audit and the
    schedule simulation on the fake mesh. Static, CPU-only: the perf
    trajectory records the communication win even on accelerator-free
    runs. Best effort (None on any failure)."""
    try:
        from rocket_tpu.analysis import sched_audit as sched_mod
        from rocket_tpu.analysis import shard_audit as shard_mod

        out = {}
        for name in targets:
            legs = {}
            for leg, env_val in (("overlap", "1"), ("baseline", "0")):
                prior = os.environ.get("ROCKET_TPU_OVERLAP")
                os.environ["ROCKET_TPU_OVERLAP"] = env_val
                try:
                    shard_rep = shard_mod.run_target(
                        shard_mod.BUILTIN_TARGETS[name]
                    )
                    sched_rep = sched_mod.run_sched_target(
                        sched_mod.SCHED_TARGETS[name]
                    )
                finally:
                    if prior is None:
                        os.environ.pop("ROCKET_TPU_OVERLAP", None)
                    else:
                        os.environ["ROCKET_TPU_OVERLAP"] = prior
                srec, crec = shard_rep.record, sched_rep.record
                legs[leg] = {
                    "collective_bytes_per_step": srec.get(
                        "collective_bytes_per_step"
                    ),
                    "n_collectives": crec.get("n_collectives"),
                    "comm_total_us": crec.get("comm_total_us"),
                    "exposed_comm_us": crec.get("exposed_comm_us"),
                    "predicted_step_time_us": crec.get(
                        "predicted_step_time_us"
                    ),
                }
            on, off = legs["overlap"], legs["baseline"]
            rec = dict(legs)
            if on["collective_bytes_per_step"] and \
                    off["collective_bytes_per_step"]:
                rec["bytes_ratio"] = round(
                    off["collective_bytes_per_step"]
                    / on["collective_bytes_per_step"], 3
                )
            if on["exposed_comm_us"] is not None and \
                    off["exposed_comm_us"]:
                rec["exposed_comm_drop_frac"] = round(
                    1.0 - on["exposed_comm_us"] / off["exposed_comm_us"], 4
                )
            out[name] = rec
        return {
            "targets": out,
            "device_kind": sched_mod.DEFAULT_DEVICE_KIND,
            "wire_dtype": os.environ.get(
                "ROCKET_TPU_OVERLAP_WIRE", "bfloat16"
            ),
        }
    except Exception:  # noqa: BLE001 — emission must never die on this
        return None


def serve_summary(requests=64, warmup_requests=8):
    """Steady-state serving throughput + latency percentiles for
    BENCH_DETAIL.json (``rocket_tpu.serve``).

    A char-LM-sized model serves a synthetic continuous-batching workload
    (mixed prompt/generation lengths, greedy) on ONE engine: a small
    warmup batch pays the two compiles, ``reset_metrics()`` zeroes the
    latency aggregates (jit caches are per-engine, so the warmup must run
    on the SAME engine), then the measured batch reflects steady-state
    serving with no compile time in the percentiles. Records tokens/sec,
    TTFT/ITL percentiles, the compiled-once counters and the pool/slot
    shape. Best effort: None on any failure — emission must never die on
    serving."""
    try:
        import numpy as np

        from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
        from rocket_tpu.serve import ServeConfig, ServeEngine

        config = TransformerConfig(
            vocab_size=128, max_seq_len=256, dim=256, num_layers=6,
            num_heads=4, dropout=0.0, activation_dtype="bfloat16",
        )
        model = TransformerLM(config)
        params = jax.jit(model.init)(jax.random.key(0))["params"]
        # Byte-identical to the serve_audit `charlm` target (including
        # the k-wave scan) so the calibration leg compares like with
        # like: k=4 amortizes the dispatch tunnel 4x per device_get.
        serve_cfg = ServeConfig(
            max_slots=8, block_len=16, prefill_chunk=32, max_model_len=256,
            decode_waves_per_dispatch=4,
        )

        def run(engine, n, seed):
            rng = np.random.default_rng(seed)
            for _ in range(n):
                plen = int(rng.integers(1, 65))
                engine.submit(
                    rng.integers(0, 128, size=plen).astype(np.int32),
                    max_new_tokens=int(rng.integers(8, 65)),
                    temperature=0.0,
                )
            engine.drain()
            return engine.report()

        engine = ServeEngine(model, params, serve_cfg)
        run(engine, warmup_requests, 1)
        engine.reset_metrics()
        report = run(engine, requests, 2)

        def _ms(block):
            return {
                k: round(v * 1e3, 3)
                for k, v in (block or {}).items() if k != "count"
            }

        dispatch = report["dispatch"]
        return {
            "config": "charlm_256",
            "requests": requests,
            "tokens_generated": report["tokens_generated"],
            "tokens_per_sec": round(report["tokens_per_sec"], 1),
            "ttft_ms": _ms(report["time_to_first_token_s"]),
            "itl_ms": _ms(report["inter_token_latency_s"]),
            "decode_traces": report["compiled"]["decode_traces"],
            "prefill_traces": report["compiled"]["prefill_traces"],
            # Tunnel amortization (ISSUE 11): decoded tokens per device
            # dispatch, host syncs actually paid, and the fraction of
            # host loop time overlapped with the in-flight dispatch.
            "waves_per_dispatch": dispatch["waves_per_dispatch"],
            "tokens_per_dispatch": dispatch["tokens_per_dispatch"],
            "device_get_count": dispatch["device_get_count"],
            "host_overlap_fraction": dispatch["host_overlap_fraction"],
            "occupancy_mean": round(report["slots"]["occupancy_mean"], 2),
            "kv_pool_mib": round(
                report["pool"]["kv_pool_bytes"] / 2**20, 1
            ),
            # Request-phase attribution (obs.reqtrace): where retained
            # requests' wall time went + ITL-gap split. The overhead
            # contract (tokens/sec with tracing on ≈ off) is gated in
            # scripts/serve_smoke.py; the bench just publishes phases.
            "phases": report["phases"],
        }
    except Exception as exc:  # noqa: BLE001 — best-effort, like the audits
        log(f"bench: serve_summary failed: {exc!r}")
        return None


def resilience_summary(timeout_s=600):
    """Goodput under an injected worker kill, through the REAL supervised
    launcher, for BENCH_DETAIL.json (``rocket_tpu.resilience``).

    Runs the resilience smoke's kill leg as a subprocess on the CPU
    backend (the accelerator stays with the bench parent — a supervised
    child grabbing the TPU mid-bench would wedge both): a checkpointed
    MLP run whose rank 0 is SIGKILLed mid-training by the fault plan
    (``ROCKET_TPU_FAULTS=kill:step=23``); the supervisor must restart it
    from the latest checkpoint and finish. Records the supervisor.json
    headline (restarts, goodput_fraction — productive wall-clock over
    total, crashed generations credited only up to their last durable
    checkpoint). Best effort: None on any failure — emission must never
    die on the resilience probe."""
    try:
        import subprocess
        import tempfile

        env = dict(os.environ)
        env["JAX_PLATFORMS"] = "cpu"
        # TPU/XLA flags from the bench parent don't apply to cpu children.
        env.pop("XLA_FLAGS", None)
        smoke = os.path.join(
            os.path.dirname(os.path.abspath(__file__)),
            "scripts", "resilience_smoke.py",
        )
        with tempfile.TemporaryDirectory(prefix="bench_resilience_") as tmp:
            out_path = os.path.join(tmp, "resilience.json")
            proc = subprocess.run(
                [sys.executable, smoke,
                 "--leg", "kill", "--json-out", out_path],
                env=env, capture_output=True, text=True, timeout=timeout_s,
            )
            if proc.returncode != 0:
                log("bench: resilience probe failed: "
                    f"{(proc.stderr or proc.stdout)[-300:]}")
                return None
            with open(out_path) as f:
                return json.load(f)
    except Exception as exc:  # noqa: BLE001 — best-effort, like the audits
        log(f"bench: resilience_summary failed: {exc!r}")
        return None


def _carry_calibration(section, prior_section):
    """Merge a committed audit section's calibration entries under the
    freshly-computed ones. A partial bench run only re-predicts the
    configs it measured; the entries it could not recompute must survive
    from the committed record or tracked model/reality drift silently
    vanishes on every ``--config`` debug run."""
    prior_cal = (prior_section or {}).get("calibration")
    if not isinstance(prior_cal, dict) or not prior_cal:
        return
    fresh = section.get("calibration")
    if not isinstance(fresh, dict) or not fresh:
        # Nothing recomputed this run — carry the committed block whole.
        section["calibration"] = prior_cal
        return
    # Per-config entries (sched: name -> entry dict) merge; a flat
    # single-entry block (serve) was fully recomputed, so fresh wins.
    for key, val in prior_cal.items():
        if isinstance(val, dict) and key not in fresh:
            fresh[key] = val


def write_detail(results, path=DETAIL_PATH, health=None, serve=None,
                 resilience=None, overlap=None, calib=None):
    """Full per-config results → a committed repo file. The stdout line
    (``format_line``) carries only the headline + one number per config;
    this file is the complete record it points at.

    MERGES into an existing file rather than overwriting: a single-config
    debugging run (``--config gpt2``) must not clobber the committed
    full-sweep record the stdout ``detail`` pointer references — neither
    its per-config records nor the audit calibration entries, which a
    partial run cannot recompute (each needs that config's measured
    value from THIS run). Best effort only — the caller guards it so a
    filesystem failure can never eat the stdout line."""
    configs = {}
    prior = {}
    try:
        with open(path) as f:
            prior = json.load(f)
        configs = {k: v for k, v in prior["configs"].items()
                   if isinstance(v, dict)}
    except Exception:  # noqa: BLE001 — any malformed prior starts fresh
        prior = {}
    for name, r in results.items():
        if "error" in r and "error" not in configs.get(name, {"error": 1}):
            # An errored re-run (debugging OOM, transient XLA failure) must
            # not destroy a committed good record — annotate it instead.
            configs[name] = dict(configs[name],
                                 last_error=str(r["error"])[:200])
        else:
            configs[name] = r
    detail = {
        # Headline from the MERGED set: a --config mlp debug run must not
        # repoint the full-sweep record's headline away from gpt2.
        "headline_metric": _pick_headline(configs).get("metric"),
        "value_policy": VALUE_POLICY,
        "configs": configs,
    }
    audit = shard_audit_summary(BUDGETS_DIR)
    if audit is not None:
        # Statically-audited SPMD cost alongside the measured throughput:
        # per-device HBM estimate + per-step collective bytes per target.
        detail["shard_audit"] = audit
    prec = prec_audit_summary(PREC_BUDGETS_DIR)
    if prec is not None:
        # Statically-audited numerics next to the measured throughput:
        # fp32-bytes fraction of the traced step + cast counts per target.
        detail["prec_audit"] = prec
    tune_rec = tune_summary(TUNE_CONFIGS_DIR)
    if tune_rec is not None:
        # Tuned-kernel config tables (rocket_tpu.tune) next to the
        # throughput they shape: per-kernel entries with the tuner's
        # measured tuned-vs-default speedup per device kind, plus this
        # run's device kind so table applicability is explicit.
        detail["tune"] = tune_rec
    sched = sched_audit_summary(results, SCHED_BUDGETS_DIR)
    if sched is not None:
        # Predicted step-time attribution (compute/memory/exposed-comm)
        # per audited target + predicted-vs-measured calibration for the
        # configs this run measured — model/reality drift is tracked.
        _carry_calibration(sched, prior.get("sched_audit"))
        detail["sched_audit"] = sched
    telemetry = telemetry_summary()
    if telemetry is not None:
        # Live-run goodput split (rocket_tpu.obs) from a telemetry-enabled
        # bench run: measured compile/data-wait/step fractions next to the
        # throughput they explain.
        detail["telemetry"] = telemetry
    if health is not None:
        # Measured health-sentinel overhead (obs.health): steps/sec with
        # the in-step sentinels + lax.cond gate on vs off, plus the
        # probe's anomaly/skip accounting. Target: overhead_frac < 0.02.
        detail["health_sentinels"] = health
    if serve is not None:
        # Steady-state serving metrics (rocket_tpu.serve): continuous-
        # batching tokens/sec + TTFT/ITL percentiles on the char-LM-sized
        # model, with the compiled-once trace counters alongside.
        detail["serve"] = serve
    if resilience is not None:
        # Measured fault tolerance (rocket_tpu.resilience): the supervised
        # launcher surviving one injected SIGKILL — restart count and
        # goodput_fraction (productive/total wall-clock, crashed
        # generations credited to their last durable checkpoint).
        # Target: goodput_fraction >= 0.5 under a single mid-run kill.
        detail["resilience"] = resilience
    if overlap is None:
        # A probe-less (budget-blown or partial) run must not drop the
        # committed on/off record — carry it like the calibrations.
        overlap = prior.get("overlap")
    if overlap is not None:
        # Overlap-on/off diff of the statically audited communication
        # (collective bytes, simulated exposed-comm time) per TP/FSDP
        # target — the comm/compute-overlap win recorded even on
        # CPU-only runs.
        detail["overlap"] = overlap
    if calib is None:
        # A probe-less run keeps the committed measured-vs-predicted
        # record (the live leg needs a capture from THIS run).
        calib = prior.get("calib")
    if calib is not None:
        # Measured-vs-predicted calibration (obs.prof + analysis.calib):
        # per-target |calibration error| + unjoined fraction from the
        # committed budgets, plus a live capture->parse->reconcile leg
        # of the gpt2 sentinel step on this run's hardware.
        detail["calib"] = calib
    serve_audit = serve_audit_summary(serve, SERVE_BUDGETS_DIR)
    if serve_audit is not None:
        # Statically-predicted serving latency/HBM (serve_audit budgets)
        # next to the measured serving record, plus the predicted-vs-
        # measured ITL/TTFT calibration — model/reality drift is tracked.
        _carry_calibration(serve_audit, prior.get("serve_audit"))
        detail["serve_audit"] = serve_audit
    mem = mem_audit_summary(MEM_BUDGETS_DIR)
    if mem is not None:
        # Statically-predicted peak HBM + saved-for-backward bytes per
        # train target (mem_audit budgets) — the liveness simulation's
        # numbers the memory self-gate verifies every CI run.
        detail["mem"] = mem
    repro = repro_audit_summary(REPRO_BUDGETS_DIR)
    if repro is not None:
        # The determinism audit's committed identities (program
        # fingerprints, exact-equality gated in CI) + RNG-discipline
        # counters — the reproducibility claim the bench numbers rest on.
        detail["repro"] = repro
    fault = fault_audit_summary(FAULT_BUDGETS_DIR)
    if fault is not None:
        # The crash-consistency audit's committed coverage (crash
        # points enumerated, supervisor states explored, handlers
        # checked — drift-gated in CI): the resume-from-any-crash claim
        # the goodput numbers rest on.
        detail["fault"] = fault
    # Atomic replace: a driver timeout mid-dump must not truncate the
    # accumulated record (the corrupt-prior recovery above would then
    # silently discard it on the next run).
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(detail, f, indent=1, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)


def format_line(results, detail_path="BENCH_DETAIL.json"):
    """The single stdout JSON line: compact headline + per-config value
    summary. Guaranteed ≤ MAX_LINE_BYTES — degrades by dropping summary
    fields (never headline fields) and asserts the invariant, so adding
    bench configs can never silently overflow the driver's tail capture
    again (round-4 verdict ask #1)."""
    headline = _pick_headline(results)
    keep = ("metric", "value", "unit", "vs_baseline", "mfu",
            "best_value", "best_mfu", "error", "history")
    line = {k: headline[k] for k in keep if k in headline}
    if isinstance(line.get("error"), str):
        # str(exc) from an XLA failure routinely runs kilobytes; the line
        # must fit the capture even when every config errors.
        line["error"] = line["error"][:400]
    line["value_policy"] = VALUE_POLICY
    others = {}
    for name, r in results.items():
        if r is headline:
            continue
        if "error" in r:
            others[name] = "ERR"
        else:
            v = r.get("value")
            others[name] = round(v, 1) if isinstance(v, (int, float)) else "?"
            if isinstance(r.get("mfu"), (int, float)):
                others[name + "_mfu"] = round(r["mfu"], 3)
    line["others"] = others
    line["detail"] = detail_path

    def dumps(d):
        return json.dumps(d, separators=(",", ":"))

    s = dumps(line)
    if len(s) > MAX_LINE_BYTES:  # drop per-config mfu summaries first
        line["others"] = {n: v for n, v in others.items()
                          if not n.endswith("_mfu")}
        s = dumps(line)
    if len(s) > MAX_LINE_BYTES:  # then the summary entirely
        line.pop("others")
        s = dumps(line)
    if len(s) > MAX_LINE_BYTES:  # then round-over-round history
        line.pop("history", None)
        s = dumps(line)
    if len(s) > MAX_LINE_BYTES:  # last resort: shrink the error text
        line["error"] = line.get("error", "")[:100]
        s = dumps(line)
    assert len(s) <= MAX_LINE_BYTES, (len(s), s[:200])
    return s


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--config", default="all", choices=["all", *BENCHES.keys()]
    )
    parser.add_argument(
        "--budget-s", type=float, default=None,
        help="soft wall-clock budget: once exceeded, remaining configs are "
             "skipped so the JSON line always reaches stdout "
             "(default: $ROCKET_BENCH_BUDGET_S or 1200)",
    )
    args = parser.parse_args()
    if args.budget_s is None:
        try:
            args.budget_s = float(os.environ.get("ROCKET_BENCH_BUDGET_S", 1200))
        except ValueError:
            log("bench: bad ROCKET_BENCH_BUDGET_S — using 1200s")
            args.budget_s = 1200.0
    _require_live_backend(
        METRIC_NAMES["gpt2" if args.config == "all" else args.config]
    )

    names = list(BENCHES) if args.config == "all" else [args.config]
    results = {}
    start = time.time()
    for name in names:
        elapsed = time.time() - start
        if elapsed > args.budget_s:
            # Over budget: stop starting configs whether or not anything
            # succeeded — a JSON line with skips/errors beats being killed
            # by an outer timeout with NOTHING on stdout. (A fast early
            # failure never trips this: elapsed must exceed the budget.)
            log(f"bench: {name} skipped (elapsed {elapsed:.0f}s > "
                f"budget {args.budget_s:.0f}s)")
            results[name] = {
                "metric": METRIC_NAMES[name], "error": "skipped: time budget"
            }
            continue
        log(f"bench: {name} ...")
        t0 = time.time()
        try:
            _reset_tune_provenance()
            results[name] = BENCHES[name]()
            prov = _tune_provenance()
            if prov is not None:
                # Which kernel configs this config actually resolved
                # (table hit vs default fallback, with the entry key) —
                # future perf-trajectory comparisons know which kernels
                # were tuned when this number was measured.
                results[name]["kernel_configs"] = prov
            if name in HISTORY and "value" in results[name]:
                # Round-over-round continuity, mean-vs-mean (ask #8).
                results[name]["history"] = dict(
                    HISTORY[name],
                    now=results[name]["value"],
                )
            log(f"bench: {name} -> {results[name]} ({time.time()-t0:.0f}s)")
        except Exception as exc:  # noqa: BLE001 — record, keep benching
            log(f"bench: {name} FAILED: {exc!r}")
            results[name] = {"metric": METRIC_NAMES[name], "error": str(exc)}

    # Sentinel-overhead probe (quick paired MLP run): measured AFTER the
    # configs so it can never eat headline budget, skipped entirely when
    # the budget is already blown.
    health = None
    if time.time() - start <= args.budget_s:
        log("bench: health sentinel overhead probe ...")
        health = health_summary()
        if health is not None:
            log(f"bench: health_summary -> {health}")

    # Serving throughput/latency probe (rocket_tpu.serve) — same budget
    # discipline as the health probe: never eats headline time.
    serve = None
    if time.time() - start <= args.budget_s:
        log("bench: serve continuous-batching probe ...")
        serve = serve_summary()
        if serve is not None:
            log(f"bench: serve_summary -> {serve}")

    # Supervised-restart goodput probe (rocket_tpu.resilience) — cpu
    # subprocesses only, same budget discipline as the health/serve probes.
    resilience = None
    if time.time() - start <= args.budget_s:
        log("bench: resilience supervised-restart probe ...")
        resilience = resilience_summary()
        if resilience is not None:
            log(f"bench: resilience_summary -> {resilience}")

    # Overlap-on/off static comm probe (parallel/collectives +
    # grad_sync) — fake-mesh compiles only, same budget discipline.
    overlap = None
    if time.time() - start <= args.budget_s:
        log("bench: overlap on/off comm probe ...")
        overlap = overlap_summary()
        if overlap is not None:
            log(f"bench: overlap_summary -> {overlap}")

    # Measured-vs-predicted calibration probe (obs.prof capture of the
    # gpt2 sentinel step reconciled against the priced DAG) — same
    # budget discipline.
    calib = None
    if time.time() - start <= args.budget_s:
        log("bench: measured-vs-predicted calibration probe ...")
        calib = calib_summary()
        if calib is not None:
            log(f"bench: calib_summary -> {calib}")

    # The stdout line is the hard contract and goes out FIRST — a kill or
    # hang during the best-effort detail write must not eat it. It still
    # ends up last in the tail capture because nothing else prints to
    # stdout after it.
    print(format_line(results), flush=True)
    try:
        write_detail(results, health=health, serve=serve,
                     resilience=resilience, overlap=overlap, calib=calib)
    except Exception as exc:  # noqa: BLE001 — detail file is best effort
        log(f"bench: could not write {DETAIL_PATH}: {exc!r}")


if __name__ == "__main__":
    main()
