"""GPT-2 124M pretraining config (BASELINE.json configs[4]).

The multi-host v4-128 shape: ('data', 'model') mesh, Megatron-style tensor
parallel params (parallel/sharding.gpt2_tp_rules), bfloat16 compute with
float32 master weights, gradient accumulation, warmup-cosine schedule. On a
single chip this runs the same program with a 1x1 mesh; on a pod slice, set
mesh_shape to the real topology (e.g. {"data": 16, "model": 4}) — XLA places
the collectives on ICI, and multi-host process wiring comes from
JAX_COORDINATOR_ADDRESS/JAX_NUM_PROCESSES env vars (see runtime/context.py).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import TokenDataset, synthetic_corpus, CharTokenizer
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    next_token_loss,
)
from rocket_tpu.parallel.sharding import gpt2_tp_rules


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--data-axis", type=int, default=None)
    parser.add_argument("--model-axis", type=int, default=1)
    parser.add_argument("--batch", type=int, default=8, help="global batch (sequences)")
    parser.add_argument("--seq-len", type=int, default=1024)
    parser.add_argument("--accum", type=int, default=1)
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--small", action="store_true", help="tiny dims for smoke runs")
    parser.add_argument(
        "--trace-at", type=int, default=None,
        help="capture a jax.profiler trace for 3 steps starting here",
    )
    parser.add_argument(
        "--scan-layers", action="store_true",
        help="lax.scan over stacked blocks (compiles one block, not 12)",
    )
    parser.add_argument(
        "--resume", action="store_true",
        help="resume from the newest checkpoint (restart after preemption)",
    )
    args = parser.parse_args()

    n_dev = len(jax.devices())
    data_axis = args.data_axis or (n_dev // args.model_axis)
    runtime = rt.Runtime(
        mesh_shape={"data": data_axis, "model": args.model_axis},
        seed=0,
        gradient_accumulation_steps=args.accum,
    )

    if args.small:
        config = TransformerConfig(
            vocab_size=512, max_seq_len=args.seq_len, dim=128, num_layers=2,
            num_heads=4, dropout=0.0,
        )
    else:
        config = TransformerConfig.gpt2_124m(max_seq_len=args.seq_len)
    if args.scan_layers:
        import dataclasses

        config = dataclasses.replace(config, scan_layers=True)
    model = TransformerLM(config)
    # Analytic param count (embeddings + 12d^2 per block) — MFU denominator.
    n_params = (
        config.vocab_size * config.dim
        + config.max_seq_len * config.dim
        + config.num_layers * 12 * config.dim * config.dim
    )

    # Corpus: byte-level over the synthetic text (stands in for the real
    # tokenized corpus; swap TokenDataset input for production data).
    text = synthetic_corpus(num_chars=2_000_000)
    tok = CharTokenizer(text)
    tokens = tok.encode(text) % config.vocab_size
    data = TokenDataset(tokens, seq_len=args.seq_len)

    steps = max(1, (len(data) // args.batch) * args.epochs)
    launcher = rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=args.batch, shuffle=True, drop_last=True),
                    rt.Module(
                        model,
                        capsules=[
                            rt.Loss(next_token_loss()),
                            rt.Optimizer(optim.adamw(weight_decay=0.1)),
                            rt.Scheduler(
                                optim.warmup_cosine_lr(
                                    6e-4, warmup_steps=max(1, steps // 50),
                                    decay_steps=steps,
                                )
                            ),
                        ],
                        param_sharding=gpt2_tp_rules() if args.model_axis > 1 else None,
                        compute_dtype=jnp.bfloat16,
                        # With --scan-layers Module auto-skips this outer
                        # remat (the scanned blocks checkpoint themselves).
                        remat=not args.small,
                    ),
                    rt.Checkpointer(output_dir="checkpoints/gpt2", save_every=1000,
                                    keep_last=3,
                                    resume_from="latest" if args.resume else None),
                    # steps/sec + MFU in the tqdm postfix; optional trace.
                    rt.Profiler(
                        trace_start=args.trace_at,
                        flops_per_sample=6.0 * n_params * args.seq_len
                        + 12.0 * config.num_layers * config.dim * args.seq_len**2,
                    ),
                    rt.Tracker(backend="jsonl", project="gpt2"),
                ],
                tag="train",
            ),
        ],
        num_epochs=args.epochs,
        statefull=True,
        runtime=runtime,
    )
    print(launcher)
    launcher.launch()


if __name__ == "__main__":
    main()
