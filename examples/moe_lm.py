"""Mixture-of-Experts char-LM with expert parallelism.

Each block's MLP is replaced by a top-2 routed expert FFN (``nn/moe.py``);
on a mesh with an 'expert' axis the stacked expert params are sharded over
it (``moe_rules``) and GSPMD lowers the dispatch/combine einsums to
all-to-alls over ICI. On one chip the same program runs with every expert
local. The router's load-balancing aux loss rides batch["moe_aux_loss"]
into ``next_token_loss`` automatically.

Try it on the virtual mesh:
``XLA_FLAGS=--xla_force_host_platform_device_count=8 python - <<'PY'
import jax; jax.config.update("jax_platforms", "cpu")
import runpy; runpy.run_path("examples/moe_lm.py", run_name="__main__")
PY``
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import CharTokenizer, TokenDataset, tiny_shakespeare
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    next_token_loss,
)
from rocket_tpu.parallel.sharding import moe_rules


def main(num_epochs: int = 2, batch_size: int = 64, seq_len: int = 128):
    parser = argparse.ArgumentParser()
    parser.add_argument("--experts", type=int, default=4)
    parser.add_argument("--expert-axis", type=int, default=None,
                        help="mesh devices on the 'expert' axis (default: all)")
    args, _ = parser.parse_known_args()

    n_dev = len(jax.devices())
    # Default: widest expert axis that divides both the device count and E.
    expert_devices = args.expert_axis or max(
        w for w in range(1, n_dev + 1)
        if n_dev % w == 0 and args.experts % w == 0
    )
    if n_dev % expert_devices or args.experts % expert_devices:
        raise SystemExit(
            f"--expert-axis {expert_devices} must divide both {n_dev} "
            f"devices and {args.experts} experts"
        )
    runtime = rt.Runtime(
        mesh_shape={"data": n_dev // expert_devices, "expert": expert_devices},
        seed=0,
    )

    text = tiny_shakespeare()
    tok = CharTokenizer(text)
    data = TokenDataset(tok.encode(text), seq_len=seq_len)

    config = TransformerConfig(
        vocab_size=tok.vocab_size, max_seq_len=seq_len, dim=128,
        num_layers=4, num_heads=4, dropout=0.0,
        num_experts=args.experts, expert_top_k=2,
    )
    model = TransformerLM(config)

    rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=batch_size, shuffle=True,
                               drop_last=True),
                    rt.Module(
                        model,
                        capsules=[
                            rt.Loss(next_token_loss()),
                            rt.Optimizer(optim.adamw(), learning_rate=1e-3),
                        ],
                        param_sharding=moe_rules(),
                    ),
                    rt.Profiler(),
                ],
                tag="train",
            )
        ],
        num_epochs=num_epochs,
        runtime=runtime,
    ).launch()


if __name__ == "__main__":
    main()
