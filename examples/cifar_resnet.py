"""CIFAR-10 ResNet-18 (BASELINE.json configs[1]).

Real CIFAR-10 when cached under ./data (torchvision layout), synthetic
separable image data otherwise. SGD momentum + cosine decay, data-parallel
over all local devices, eval with gathered accuracy.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# LeNet/ResNet first-compiles take minutes on TPU; the persistent compile
# cache makes example re-runs instant. (Deserialized executables run slower
# steady-state on the tunneled chip, so the cache is opt-in — acceptable here
# where compile time dominates, wrong for bench.py.)
os.environ.setdefault("ROCKET_TPU_CACHE", "1")

import numpy as np
import optax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.datasets import ArrayDataset
from rocket_tpu.data.augment import image_augment
from rocket_tpu.models.resnet import resnet18
from rocket_tpu.utils.metrics import Accuracy


def cifar10(train=True):
    try:
        from torchvision.datasets import CIFAR10

        tv = CIFAR10(root=os.environ.get("CIFAR_ROOT", "data"), train=train, download=False)
        images = tv.data.astype(np.float32) / 255.0  # (N, 32, 32, 3) NHWC already
        mean = np.asarray([0.4914, 0.4822, 0.4465], np.float32)
        std = np.asarray([0.247, 0.243, 0.261], np.float32)
        images = (images - mean) / std
        labels = np.asarray(tv.targets, np.int32)
        return ArrayDataset(images, labels)
    except Exception:
        rng = np.random.default_rng(0 if train else 1)
        n = 50_000 if train else 10_000
        labels = rng.integers(0, 10, size=n).astype(np.int32)
        templates = np.random.default_rng(7).normal(size=(10, 32, 32, 3)).astype(np.float32)
        images = templates[labels] + rng.normal(size=(n, 32, 32, 3)).astype(np.float32) * 0.6
        return ArrayDataset(images, labels)


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def main(num_epochs: int = 5, batch_size: int = 512):
    runtime = rt.Runtime(seed=0)
    model = resnet18(num_classes=10, stem="cifar")
    accuracy = Accuracy()

    train_data = cifar10(train=True)
    steps = max(1, len(train_data) // batch_size * num_epochs)

    launcher = rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(train_data, batch_size=batch_size, shuffle=True,
                               drop_last=True),
                    rt.Module(
                        model,
                        capsules=[
                            rt.Loss(cross_entropy),
                            rt.Optimizer(optim.momentum(beta=0.9)),
                            rt.Scheduler(optim.cosine_lr(0.2, decay_steps=steps)),
                        ],
                        # On-device augmentation: the host ships raw samples
                        # once (device-cached); each step crops+flips with
                        # its own PRNG fold inside the compiled step.
                        batch_transform=image_augment(crop_padding=4, flip=True),
                    ),
                    rt.Checkpointer(output_dir="checkpoints/cifar", save_every=200,
                                    keep_last=2),
                    rt.Tracker(backend="jsonl", project="cifar_resnet18"),
                ],
                tag="train",
            ),
            rt.Looper(
                [
                    rt.Dataset(cifar10(train=False), batch_size=batch_size),
                    rt.Module(model),
                    rt.Meter(["logits", "label"], [accuracy]),
                    rt.Tracker(backend="jsonl", project="cifar_resnet18"),
                ],
                tag="val",
                grad_enabled=False,
            ),
        ],
        num_epochs=num_epochs,
        statefull=True,
        runtime=runtime,
    )
    launcher.launch()
    print(f"val accuracy: {accuracy.value:.4f}")


if __name__ == "__main__":
    main()
