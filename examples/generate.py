"""Sample text from a trained char-LM checkpoint — the decode path as a
user-facing artifact (round-4 verdict ask #8).

Loads params from a ``char_lm.py`` checkpoint (or trains a short run
first when none exists), then generates with :func:`generate`: one
compiled prefill + incremental decode through per-layer KV caches; when
the cache shape qualifies, single-token attention runs the fused pallas
decode kernel (``ops/decode_attention.py``) automatically.

    python examples/char_lm.py                 # train + checkpoint
    python examples/generate.py --prompt "KING: " --tokens 200
    python examples/generate.py --greedy       # argmax decode
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

from rocket_tpu.core.checkpoint import Checkpointer
from rocket_tpu.data.text import CharTokenizer, tiny_shakespeare
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    generate,
)
from rocket_tpu.runtime import checkpoint_io

SEQ_LEN = 256  # must match char_lm.py's training config


def load_params(model, ckpt_dir: str):
    """Newest complete checkpoint's params, restored onto the device via
    the resharding reader (the checkpoint may have been written by any
    process count / sharding)."""
    latest = Checkpointer(
        output_dir=ckpt_dir, resume_from="latest"
    )._resolve_resume_path("latest")
    if latest is None:
        return None
    template = {"params": jax.jit(model.init)(jax.random.key(0))["params"]}
    restored = checkpoint_io.load_pytree(
        os.path.join(latest, "model_0"), template
    )
    print(f"loaded params from {latest}")
    return restored["params"]


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--ckpt", default="checkpoints/char_lm",
                        help="checkpoint dir written by char_lm.py")
    parser.add_argument("--prompt", default="the ")
    parser.add_argument("--tokens", type=int, default=128,
                        help="tokens to generate")
    parser.add_argument("--temperature", type=float, default=0.8)
    parser.add_argument("--top-k", type=int, default=20)
    parser.add_argument("--top-p", type=float, default=None)
    parser.add_argument("--greedy", action="store_true",
                        help="argmax decode (ignores temperature/top-k/p)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--bench", action="store_true",
                        help="also report decode throughput (tok/s) over a "
                        "second, timed generation")
    args = parser.parse_args()

    # The tokenizer is a pure function of the corpus — rebuild it rather
    # than persisting vocab files.
    tok = CharTokenizer(tiny_shakespeare())
    # Architecture comes from the checkpoint dir's config.json when
    # present: param shapes are head-count independent, so loading params
    # trained under a different preset would silently sample garbage.
    import json

    cfg_path = os.path.join(args.ckpt, "config.json")
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            config = TransformerConfig(**json.load(f))
        print(f"using architecture from {cfg_path} "
              f"(heads={config.num_heads}, dim={config.dim})")
    else:
        config = TransformerConfig.char_lm(
            vocab_size=tok.vocab_size, max_seq_len=SEQ_LEN
        )
        print("no config.json next to the checkpoints — assuming the "
              f"current char_lm preset (heads={config.num_heads}); "
              "checkpoints from an older preset will sample garbage")
    model = TransformerLM(config)

    params = load_params(model, args.ckpt)
    if params is None:
        print(f"no checkpoint under {args.ckpt!r} — training one first "
              "(examples/char_lm.py, 1 epoch)...")
        import examples.char_lm as char_lm

        char_lm.main(num_epochs=1)
        params = load_params(model, args.ckpt)
        if params is None:
            raise SystemExit(
                "char_lm.py finished but left no complete checkpoint under "
                f"{args.ckpt!r}"
            )

    prompt = tok.encode(args.prompt)[None, :]
    max_new = min(args.tokens, config.max_seq_len - prompt.shape[1])
    if max_new < args.tokens:
        print(f"clamping to {max_new} tokens (max_seq_len={config.max_seq_len})")
    out = generate(
        model, {"params": params, "state": {}}, prompt, max_new,
        key=jax.random.key(args.seed),
        temperature=0.0 if args.greedy else args.temperature,
        top_k=None if args.greedy else args.top_k,
        top_p=None if args.greedy else args.top_p,
    )
    print("-" * 60)
    print(tok.decode(np.asarray(out[0])))

    if args.bench:
        # The first call above paid the compile; time a steady-state one.
        import time

        t0 = time.perf_counter()
        out2 = generate(
            model, {"params": params, "state": {}}, prompt, max_new,
            key=jax.random.key(args.seed + 1),
            temperature=0.0 if args.greedy else args.temperature,
            top_k=None if args.greedy else args.top_k,
            top_p=None if args.greedy else args.top_p,
        )
        np.asarray(out2)  # true sync
        dt = time.perf_counter() - t0
        print(f"decode: {max_new} tokens in {dt*1e3:.0f} ms = "
              f"{max_new/dt:,.0f} tok/s (B=1, KV-cached incremental decode)")


if __name__ == "__main__":
    main()
