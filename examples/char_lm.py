"""Char-transformer on TinyShakespeare (BASELINE.json configs[2]).

Canonical capsule tree for LM training: device-cached token dataset, fused
jitted train step (AdamW + warmup-cosine), val phase with loss metric.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import CharTokenizer, TokenDataset, tiny_shakespeare
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    next_token_loss,
)


def main(num_epochs: int = 2, batch_size: int = 128, seq_len: int = 256):
    text = tiny_shakespeare()
    tok = CharTokenizer(text)
    tokens = tok.encode(text)
    split = int(len(tokens) * 0.95)
    train_data = TokenDataset(tokens[:split], seq_len=seq_len)
    val_data = TokenDataset(tokens[split:], seq_len=seq_len)

    runtime = rt.Runtime(seed=0)
    config = TransformerConfig.char_lm(vocab_size=tok.vocab_size, max_seq_len=seq_len)
    model = TransformerLM(config)

    # Persist the architecture next to the checkpoints: param SHAPES are
    # head-count independent (the fused QKV projection is (D, 3D) for any
    # split), so a later load under a different preset would succeed and
    # silently compute a different function. generate.py reads this back.
    import dataclasses
    import json

    os.makedirs("checkpoints/char_lm", exist_ok=True)
    with open("checkpoints/char_lm/config.json", "w") as f:
        json.dump(dataclasses.asdict(config), f, indent=1)

    steps_per_epoch = len(train_data) // batch_size
    total_steps = max(1, steps_per_epoch * num_epochs)

    module = rt.Module(
        model,
        capsules=[
            rt.Loss(next_token_loss()),
            rt.Optimizer(optim.adamw(weight_decay=0.1)),
            rt.Scheduler(
                optim.warmup_cosine_lr(
                    3e-4, warmup_steps=max(1, total_steps // 20),
                    decay_steps=total_steps,
                )
            ),
        ],
    )

    # Keep a handle on the trained params past destroy (for sampling below).
    trained = {}

    class Keep(rt.Capsule):
        def __init__(self):
            super().__init__(priority=10)

        def launch(self, attrs=None):
            trained["params"] = module.state["params"]

    launcher = rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(train_data, batch_size=batch_size, shuffle=True,
                               drop_last=True),
                    module,
                    Keep(),
                    # Save at every epoch boundary: the corpus is small
                    # (~34 steps/epoch at these defaults), so a large fixed
                    # save_every would never fire and examples/generate.py
                    # would find no checkpoint to sample from.
                    rt.Checkpointer(output_dir="checkpoints/char_lm",
                                    save_every=steps_per_epoch, keep_last=2),
                    rt.Tracker(backend="jsonl", project="char_lm"),
                ],
                tag="train",
            ),
        ],
        num_epochs=num_epochs,
        statefull=True,
        runtime=runtime,
    )
    launcher.launch()
    print(f"vocab={tok.vocab_size} steps={total_steps}")

    # Sample a continuation from the trained model (generate() prefills the
    # prompt, then decodes through per-layer KV caches in one compiled loop).
    from rocket_tpu.models.transformer import generate

    prompt = tok.encode("the ")[None, :]
    max_new = min(64, config.max_seq_len - prompt.shape[1])
    out = generate(
        model, {"params": trained["params"], "state": {}}, prompt, max_new,
        key=jax.random.key(0), temperature=0.8, top_k=20,
    )
    print("sample:", tok.decode(np.asarray(out[0])))


if __name__ == "__main__":
    main()
