"""MNIST with the canonical capsule tree.

The TPU-native analogue of the reference's example (``examples/mnist.py:76-107``)
— same composition: LeNet, whole-batch cross-entropy objective, AdamW +
StepLR, gradient accumulation 2, train/val loopers, Meter/Accuracy,
Checkpointer, Tracker — with the reference's bugs fixed (its version never
calls ``launch()`` and crashes on an unimported name; SURVEY §2a Example row).

Run: ``python examples/mnist.py`` (uses real MNIST if cached under ./data,
synthetic otherwise).
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# LeNet/ResNet first-compiles take minutes on TPU; the persistent compile
# cache makes example re-runs instant. (Deserialized executables run slower
# steady-state on the tunneled chip, so the cache is opt-in — acceptable here
# where compile time dominates, wrong for bench.py.)
os.environ.setdefault("ROCKET_TPU_CACHE", "1")

import optax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.datasets import mnist
from rocket_tpu.models.lenet import LeNet
from rocket_tpu.utils.metrics import Accuracy


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def main(num_epochs: int = 3, batch_size: int = 1024):
    runtime = rt.Runtime(seed=0, gradient_accumulation_steps=2)

    model = LeNet(num_classes=10)
    train_data = mnist(train=True)
    val_data = mnist(train=False)
    accuracy = Accuracy()

    launcher = rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(train_data, batch_size=batch_size, shuffle=True),
                    rt.Module(
                        model,
                        capsules=[
                            rt.Loss(cross_entropy),
                            rt.Optimizer(optim.adamw(weight_decay=0.01)),
                            rt.Scheduler(optim.step_lr(1e-3, step_size=100, gamma=0.5)),
                        ],
                    ),
                    rt.Checkpointer(output_dir="checkpoints/mnist", save_every=50),
                    rt.Tracker(backend="jsonl", project="mnist"),
                ],
                tag="train",
            ),
            rt.Looper(
                [
                    rt.Dataset(val_data, batch_size=batch_size),
                    rt.Module(model),
                    rt.Meter(["logits", "label"], [accuracy]),
                    rt.Tracker(backend="jsonl", project="mnist"),
                ],
                tag="val",
                grad_enabled=False,
            ),
        ],
        num_epochs=num_epochs,
        statefull=True,
        runtime=runtime,
    )
    print(launcher)
    launcher.launch()
    print(f"val accuracy: {accuracy.value:.4f}")
    return accuracy.value


if __name__ == "__main__":
    main()
