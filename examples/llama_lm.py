"""Llama-family char LM — the second model family, end to end.

Same capsule tree as ``char_lm.py`` but the model uses the Llama recipe:
RoPE positions (no learned table), RMSNorm, SwiGLU FFN, grouped-query
attention (half the K/V heads -> half the KV cache in decode), untied
head, gradient clipping, and nucleus sampling at the end. Runs anywhere:
the real chip, or ``JAX_PLATFORMS=cpu
XLA_FLAGS=--xla_force_host_platform_device_count=8 python
examples/llama_lm.py``.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import CharTokenizer, TokenDataset, tiny_shakespeare
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    generate,
    next_token_loss,
)


def main(num_epochs: int = 2, batch_size: int = 128, seq_len: int = 256):
    text = tiny_shakespeare()
    tok = CharTokenizer(text)
    tokens = tok.encode(text)
    train_data = TokenDataset(tokens, seq_len=seq_len)

    runtime = rt.Runtime(seed=0)
    config = TransformerConfig.llama_style(
        vocab_size=tok.vocab_size, max_seq_len=seq_len,
        dim=256, num_layers=6, num_heads=8, num_kv_heads=4,
    )
    config.loss_chunk = 64
    model = TransformerLM(config)

    steps_per_epoch = len(train_data) // batch_size
    total_steps = max(1, steps_per_epoch * num_epochs)

    module = rt.Module(
        model,
        capsules=[
            rt.Loss(next_token_loss()),
            rt.Optimizer(optim.adamw(weight_decay=0.1), clip_norm=1.0),
            rt.Scheduler(
                optim.warmup_cosine_lr(
                    3e-4, warmup_steps=max(1, total_steps // 20),
                    decay_steps=total_steps,
                )
            ),
        ],
    )

    trained = {}

    class Keep(rt.Capsule):
        def __init__(self):
            super().__init__(priority=10)

        def launch(self, attrs=None):
            trained["params"] = module.state["params"]

    rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(train_data, batch_size=batch_size, shuffle=True,
                               drop_last=True),
                    module,
                    Keep(),
                    rt.Checkpointer(output_dir="checkpoints/llama_lm", save_every=500),
                ],
                tag="train",
            ),
        ],
        num_epochs=num_epochs,
        statefull=True,
        runtime=runtime,
    ).launch()
    print(f"vocab={tok.vocab_size} steps={total_steps}")

    # Nucleus sampling through the GQA KV cache (half-size by design).
    prompt = tok.encode("the ")[None, :]
    max_new = min(64, config.max_seq_len - prompt.shape[1])
    out = generate(
        model, {"params": trained["params"], "state": {}}, prompt, max_new,
        key=jax.random.key(0), temperature=0.8, top_p=0.9,
    )
    print("sample:", tok.decode(np.asarray(out[0])))


if __name__ == "__main__":
    main()
