"""Long-context LM training with ring-attention sequence parallelism.

The promised long-context example (``parallel/ring_attention.py``): sequences
longer than one chip's HBM can hold are sharded over a 'seq' mesh axis — each
device keeps T/n tokens of every activation, and attention exchanges K/V
blocks around the ring over ICI (``impl="ring"``) instead of materializing
the full (T, T) score matrix anywhere.

On a v4-32 you would run e.g. ``--seq-devices 16 --seq-len 131072``; the
defaults are sized to run on any host (including the virtual 8-device CPU
mesh: ``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
python examples/long_context.py``).
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import TokenDataset, synthetic_corpus, CharTokenizer
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    next_token_loss,
)


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--seq-devices", type=int, default=None,
                        help="mesh devices on the 'seq' axis (default: all)")
    parser.add_argument("--seq-len", type=int, default=4096)
    parser.add_argument("--batch", type=int, default=2)
    parser.add_argument("--dim", type=int, default=256)
    parser.add_argument("--layers", type=int, default=4)
    parser.add_argument("--epochs", type=int, default=1)
    args = parser.parse_args()

    n_dev = len(jax.devices())
    seq_devices = args.seq_devices or n_dev
    if n_dev % seq_devices or n_dev < seq_devices:
        raise SystemExit(
            f"--seq-devices {seq_devices} must divide the {n_dev} available "
            "devices (on one chip, run under a virtual CPU mesh — see module "
            "docstring)."
        )
    data_devices = n_dev // seq_devices
    if args.seq_len % seq_devices:
        raise SystemExit(f"--seq-len must divide over {seq_devices} seq devices")

    # The 'seq' mesh axis turns on sequence sharding in Runtime.shard_batch
    # (token dim sharded) and is what impl="ring" rotates K/V around.
    runtime = rt.Runtime(
        mesh_shape={"data": data_devices, "seq": seq_devices}, seed=0
    )

    config = TransformerConfig(
        vocab_size=256,
        max_seq_len=args.seq_len,
        dim=args.dim,
        num_layers=args.layers,
        num_heads=max(4, args.dim // 64),
        dropout=0.0,
        attention_impl="ring",
        activation_dtype="bfloat16",
    )
    model = TransformerLM(config)

    text = synthetic_corpus(num_chars=max(4 * args.seq_len * args.batch, 200_000))
    tok = CharTokenizer(text)
    data = TokenDataset(tok.encode(text) % config.vocab_size, seq_len=args.seq_len)

    launcher = rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=args.batch, shuffle=True,
                               drop_last=True),
                    rt.Module(
                        model,
                        capsules=[
                            rt.Loss(next_token_loss()),
                            rt.Optimizer(optim.adamw(), learning_rate=3e-4),
                        ],
                        remat=True,
                    ),
                    rt.Profiler(),
                ],
                tag="train",
            )
        ],
        num_epochs=args.epochs,
        runtime=runtime,
    )
    print(launcher)
    launcher.launch()


if __name__ == "__main__":
    main()
