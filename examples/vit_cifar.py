"""CIFAR-10 Vision Transformer — the third transformer family.

Non-causal encoder over patches (``rocket_tpu.models.vit``): same capsule
tree shape as ``cifar_resnet.py`` (train looper with on-device
augmentation + eval looper with gathered accuracy), AdamW + warmup-cosine,
bf16 compute. Real CIFAR-10 when cached under ./data, synthetic separable
data otherwise.
"""

import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

os.environ.setdefault("ROCKET_TPU_CACHE", "1")

import jax.numpy as jnp

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.augment import image_augment
from rocket_tpu.models.vit import vit_tiny
from rocket_tpu.utils.metrics import Accuracy

from cifar_resnet import cifar10, cross_entropy  # shared data + objective


def main(num_epochs: int = 5, batch_size: int = 512):
    runtime = rt.Runtime(seed=0)
    model = vit_tiny(image_size=32, patch_size=4, num_classes=10, dropout=0.1)
    accuracy = Accuracy()
    train_data = cifar10(train=True)
    steps = max(1, len(train_data) // batch_size * num_epochs)

    launcher = rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(train_data, batch_size=batch_size, shuffle=True,
                               drop_last=True),
                    rt.Module(
                        model,
                        capsules=[
                            rt.Loss(cross_entropy),
                            rt.Optimizer(optim.adamw(), clip_norm=1.0),
                            rt.Scheduler(optim.warmup_cosine_lr(
                                3e-3, warmup_steps=max(1, steps // 20),
                                decay_steps=steps,
                            )),
                        ],
                        compute_dtype=jnp.bfloat16,
                        batch_transform=image_augment(crop_padding=4, flip=True),
                    ),
                    rt.Checkpointer(output_dir="checkpoints/vit_cifar",
                                    save_every=200, keep_last=2),
                    rt.Tracker(backend="jsonl", project="vit_cifar"),
                ],
                tag="train",
            ),
            rt.Looper(
                [
                    rt.Dataset(cifar10(train=False), batch_size=batch_size),
                    rt.Module(model, compute_dtype=jnp.bfloat16),
                    rt.Meter(["logits", "label"], [accuracy]),
                    rt.Tracker(backend="jsonl", project="vit_cifar"),
                ],
                tag="val",
                grad_enabled=False,
            ),
        ],
        num_epochs=num_epochs,
        statefull=True,
        runtime=runtime,
    )
    launcher.launch()
    print(f"val accuracy: {accuracy.value:.4f}")


if __name__ == "__main__":
    main()
