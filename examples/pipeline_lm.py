"""Pipeline-parallel LM training — GPipe or 1F1B over a 'pipe' mesh axis.

The transformer's stacked layers (``scan_layers=True``) are sharded per
stage over the 'pipe' axis; microbatches ``ppermute`` between stages
inside one compiled program (``parallel/pipeline.py``). Two schedules:

* ``--schedule gpipe`` (default): forward pipeline differentiated by
  autodiff — simple, but per-stage live activations grow with the
  microbatch count;
* ``--schedule 1f1b``: loss and backward run INSIDE the pipelined
  program (one-forward-one-backward interleave) — per-stage live
  activations are O(stages), the standard at real pipeline depth.

On real hardware you would run e.g. ``--pipe-devices 4`` on a v4-8 slice;
the defaults run anywhere, including the virtual CPU mesh:
``JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8
python examples/pipeline_lm.py --schedule 1f1b``.
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax

# Some environments pre-import jax at interpreter startup, which makes the
# JAX_PLATFORMS env var alone too late — honor it through the config too.
if os.environ.get("JAX_PLATFORMS") == "cpu":
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.data.text import CharTokenizer, TokenDataset, synthetic_corpus
from rocket_tpu.models.transformer import (
    TransformerConfig,
    TransformerLM,
    next_token_loss,
)
from rocket_tpu.parallel.sharding import pipeline_rules


def main():
    parser = argparse.ArgumentParser()
    parser.add_argument("--schedule", choices=["gpipe", "1f1b"],
                        default="gpipe")
    parser.add_argument("--pipe-devices", type=int, default=None,
                        help="pipeline stages (default: half the devices)")
    parser.add_argument("--microbatches", type=int, default=4)
    parser.add_argument("--epochs", type=int, default=2)
    args = parser.parse_args()

    n = len(jax.devices())
    pipe = args.pipe_devices or max(2, n // 2)
    if n % pipe or pipe < 2:
        raise SystemExit(
            f"--pipe-devices {pipe} must be >= 2 and divide the {n} "
            "available devices; on one chip run under a virtual CPU mesh "
            "— see the module docstring."
        )
    data_par = n // pipe
    runtime = rt.Runtime(mesh_shape={"data": data_par, "pipe": pipe}, seed=0)

    corpus = synthetic_corpus(num_chars=60_000)
    tok = CharTokenizer(corpus)
    seq_len = 64
    data = TokenDataset(tok.encode(corpus), seq_len=seq_len)

    config = TransformerConfig(
        vocab_size=tok.vocab_size, max_seq_len=seq_len, dim=64,
        num_layers=2 * pipe, num_heads=4, dropout=0.0,
        scan_layers=True, pipeline_axis="pipe",
        pipeline_microbatches=args.microbatches,
        pipeline_schedule=args.schedule,
        loss_chunk=32,
    )
    module = rt.Module(
        TransformerLM(config),
        capsules=[
            rt.Loss(next_token_loss()),
            rt.Optimizer(optim.adamw(), learning_rate=3e-3),
        ],
        param_sharding=pipeline_rules(),
    )

    losses = []

    class Spy(rt.Capsule):
        def __init__(self):
            super().__init__(priority=500)

        def launch(self, attrs=None):
            if attrs.looper.state.loss is not None:
                # Device scalar — converted to host floats ONCE after the
                # run (a float() here would block the pipeline every step).
                losses.append(attrs.looper.state.loss)

    batch_size = 8 * data_par * args.microbatches
    if batch_size > len(data):
        raise SystemExit(
            f"batch size {batch_size} exceeds the {len(data)}-sequence "
            "dataset; lower --microbatches."
        )
    rt.Launcher(
        [rt.Looper(
            [rt.Dataset(data, batch_size=batch_size,
                        drop_last=True, shuffle=True),
             module, Spy()],
            tag="train", progress=False,
        )],
        num_epochs=args.epochs,
        runtime=runtime,
    ).launch()
    first, last = float(np.asarray(losses[0])), float(np.asarray(losses[-1]))
    print(f"{args.schedule} over {pipe} stages x {data_par} data shards: "
          f"loss {first:.3f} -> {last:.3f} ({len(losses)} steps)")
    assert last < first, "loss did not improve"


if __name__ == "__main__":
    main()
