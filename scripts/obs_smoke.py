#!/usr/bin/env python
"""Obs smoke gate: a tier-1 CPU example run with telemetry ON must emit a
parseable telemetry.json whose goodput categories sum to the run's
wall-clock (within 5%), a span file Perfetto can load (valid Chrome-trace
JSON), and obs/* scalars in the tracker stream — all with
``Runtime(strict=True)`` active, proving the instrumentation adds no
host-sync to the step path. Exits non-zero on the first violated
invariant (wired into scripts/check.sh and CI).
"""

import json
import os
import subprocess
import sys
import tempfile

# Same backend bootstrap as tests/conftest.py: 8 virtual CPU devices,
# configured before jax picks a backend.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402

import rocket_tpu as rt  # noqa: E402
from rocket_tpu import optim  # noqa: E402
from rocket_tpu.models.mlp import MLP  # noqa: E402
from rocket_tpu.obs.spans import load_chrome_trace  # noqa: E402


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def check(condition, message):
    if not condition:
        print(f"obs smoke FAILED: {message}", file=sys.stderr)
        sys.exit(1)


def main() -> None:
    # Workdir under the repo's (gitignored) runs/ — NOT the system tmpdir —
    # so a failing CI run's telemetry lands inside the workspace where the
    # runs/** artifact-upload step can find it.
    repo_runs = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "runs"
    )
    os.makedirs(repo_runs, exist_ok=True)
    workdir = tempfile.mkdtemp(prefix="obs_smoke_", dir=repo_runs)
    runs_dir = os.path.join(workdir, "runs")
    rng = np.random.default_rng(0)
    data = [
        {"image": rng.normal(size=8).astype(np.float32),
         "label": np.int32(i % 4)}
        for i in range(256)
    ]
    # strict=True: the run-wide D2H guard + per-wave full transfer guard
    # stay green with the obs instrumentation active (the self-gate half
    # of the acceptance criteria; rocketlint covers the static half).
    # health=True: the sentinel-instrumented step path — health word
    # computed in-jit, fetched lagged+explicit — must ALSO stay sync-free
    # under the guards.
    runtime = rt.Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=workdir,
        strict=True, telemetry=True, watchdog_secs=120.0,
        health=True, anomaly_action="skip_step",
    )
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=32),
                    module,
                    rt.Profiler(),
                    rt.Tracker(project="smoke", directory=runs_dir),
                ],
                tag="train", progress=False,
            )
        ],
        num_epochs=2,
        runtime=runtime,
    ).launch()

    out_dir = os.path.join(runs_dir, "smoke")
    telemetry_path = os.path.join(out_dir, "telemetry.json")
    check(os.path.exists(telemetry_path), f"{telemetry_path} not written")
    with open(telemetry_path) as f:
        record = json.load(f)

    goodput = record["goodput"]
    total = goodput["total_wall_s"]
    cat_sum = sum(goodput["categories"].values())
    check(total > 0, "zero total wall-clock")
    check(
        abs(cat_sum - total) <= 0.05 * total,
        f"goodput categories sum {cat_sum:.4f}s != total {total:.4f}s",
    )
    check(goodput["categories"]["step"] > 0, "no step time accounted")
    check(goodput["categories"]["compile"] > 0, "no compile time accounted")

    spans_path = os.path.join(out_dir, record["spans"]["file"])
    events = load_chrome_trace(spans_path)
    complete = [e for e in events if e.get("ph") == "X"]
    check(len(complete) > 0, "span file has no complete spans")
    cats = {e.get("cat") for e in complete}
    check({"step", "compile", "data_wait", "flush"} <= cats,
          f"span categories incomplete: {sorted(cats)}")

    # Health sentinels ran on every step of this clean run: the decoded
    # gauges are present, nothing anomalous, nothing skipped.
    health = record.get("health")
    check(health is not None, "no health section in telemetry.json")
    check(health["anomalies"] == 0,
          f"clean run reported {health['anomalies']} anomalies")
    check(health["skipped_steps"] == 0,
          f"clean run skipped {health['skipped_steps']} steps")
    check(health["last_good_step"] is not None, "no health word decoded")
    gauges = record["metrics"]["gauges"]
    for key in ("health/grad_norm", "health/update_ratio",
                "health/last_good_step"):
        check(key in gauges, f"{key} missing from the registry snapshot")

    # obs/* scalars landed in the tracker backend stream.
    jsonl = os.path.join(runs_dir, "smoke.jsonl")
    with open(jsonl) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    check(any(k.startswith("obs/") for rec in lines for k in rec),
          "no obs/* scalars in the tracker stream")
    check(any(k.startswith("health/") for rec in lines for k in rec),
          "no health/* scalars in the tracker stream")

    # The report CLI renders both files.
    for path in (telemetry_path, spans_path):
        proc = subprocess.run(
            [sys.executable, "-m", "rocket_tpu.obs", "report", path],
            capture_output=True, text=True,
        )
        check(proc.returncode == 0,
              f"report CLI failed on {path}: {proc.stderr[-300:]}")

    print(
        "obs smoke OK: "
        f"goodput step={goodput['fractions']['step']:.1%} "
        f"compile={goodput['fractions']['compile']:.1%}, "
        f"{len(complete)} spans, health sentinels green "
        f"(last good step {health['last_good_step']}), strict guards green"
    )


if __name__ == "__main__":
    main()
