#!/usr/bin/env python
"""Obs smoke gate: a tier-1 CPU example run with telemetry ON must emit a
parseable telemetry.json whose goodput categories sum to the run's
wall-clock (within 5%), a span file Perfetto can load (valid Chrome-trace
JSON), and obs/* scalars in the tracker stream — all with
``Runtime(strict=True)`` active, proving the instrumentation adds no
host-sync to the step path.

The capture->parse->reconcile leg (ISSUE 13): the run's Profiler
captures a mid-run device-trace window (perfetto trace-event output),
whose parse must land ``obs/prof/*`` gauges in telemetry.json and whose
file ``python -m rocket_tpu.obs prof`` must render as a nonempty
per-op attribution table; then ``python -m rocket_tpu.analysis calib
--target gpt2_sentinel`` must capture a fresh trace of the gpt2
sentinel step, reconcile it against the priced optimized-HLO DAG and
hold the committed calibration budget (exit 0).

The live-export leg (ISSUE 19): the same run streams telemetry shards
(``Runtime(export=True)``) and mounts the ``/metrics`` endpoint
(``metrics_port=0``), still under the strict guards — exporting must add
zero device syncs. A poller scrapes mid-run (the endpoint tears down
with the run) and the scrape must carry goodput + SLO families; a
seeded SLO violation must be detected online (``obs/slo/*`` counter)
and gate offline (``obs watch`` exit 1), while a slack spec passes;
``obs top --once`` must render the shard fleet view.

Exits non-zero on the first violated invariant (wired into
scripts/check.sh and CI).
"""

import json
import os
import re
import subprocess
import sys
import tempfile
import threading
import urllib.request

# Same backend bootstrap as tests/conftest.py: 8 virtual CPU devices,
# configured before jax picks a backend.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402

import rocket_tpu as rt  # noqa: E402
from rocket_tpu import optim  # noqa: E402
from rocket_tpu.models.mlp import MLP  # noqa: E402
from rocket_tpu.obs.spans import load_chrome_trace  # noqa: E402


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def check(condition, message):
    if not condition:
        print(f"obs smoke FAILED: {message}", file=sys.stderr)
        sys.exit(1)


def main() -> None:
    # Workdir under the repo's (gitignored) runs/ — NOT the system tmpdir —
    # so a failing CI run's telemetry lands inside the workspace where the
    # runs/** artifact-upload step can find it.
    repo_runs = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "runs"
    )
    os.makedirs(repo_runs, exist_ok=True)
    workdir = tempfile.mkdtemp(prefix="obs_smoke_", dir=repo_runs)
    runs_dir = os.path.join(workdir, "runs")
    rng = np.random.default_rng(0)
    data = [
        {"image": rng.normal(size=8).astype(np.float32),
         "label": np.int32(i % 4)}
        for i in range(256)
    ]
    # Seeded SLO specs with deterministic verdicts (the committed
    # default:train/serve specs encode TPU roofline objectives — CPU toy
    # timing would make their verdicts flaky here). health/grad_norm is
    # a positive gauge on every clean run: a ceiling of 1e-30 MUST
    # violate, a ceiling of 1e12 MUST hold.
    violating_spec = os.path.join(workdir, "slo_violating.json")
    passing_spec = os.path.join(workdir, "slo_passing.json")
    for path, objective in ((violating_spec, 1e-30), (passing_spec, 1e12)):
        with open(path, "w") as f:
            json.dump({"version": 1, "slos": [
                {"name": "seeded_grad_ceiling", "kind": "gauge_max",
                 "metric": "health/grad_norm", "objective": objective},
            ]}, f)
    # strict=True: the run-wide D2H guard + per-wave full transfer guard
    # stay green with the obs instrumentation active (the self-gate half
    # of the acceptance criteria; rocketlint covers the static half).
    # health=True: the sentinel-instrumented step path — health word
    # computed in-jit, fetched lagged+explicit — must ALSO stay sync-free
    # under the guards.
    # export=True + metrics_port=0: the live plane (shards, /metrics,
    # online SLO evaluation) runs the whole time — under the same strict
    # guards, proving exporting adds zero device syncs to the step path.
    runtime = rt.Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=workdir,
        strict=True, telemetry=True, watchdog_secs=120.0,
        health=True, anomaly_action="skip_step",
        export=True, export_interval_s=0.2, metrics_port=0,
        slo=violating_spec,
    )
    exporter = runtime.telemetry.exporter
    check(exporter is not None and exporter.server is not None,
          "export=True + metrics_port=0 did not mount the live plane")
    metrics_url = f"http://127.0.0.1:{exporter.server.port}/metrics"
    # The endpoint lives exactly as long as the run (end_training stops
    # it), so the scrape must happen MID-RUN: poll from a thread, keep
    # the last successful body.
    scrape = {"body": "", "n": 0}
    scraping = threading.Event()

    def _poll():
        while not scraping.wait(0.1):
            try:
                with urllib.request.urlopen(metrics_url, timeout=2) as resp:
                    scrape["body"] = resp.read().decode()
                    scrape["n"] += 1
            except OSError:
                pass

    poller = threading.Thread(target=_poll, daemon=True)
    poller.start()
    model = MLP(in_features=8, num_classes=4, hidden=(16,))
    module = rt.Module(
        model,
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    rt.Launcher(
        [
            rt.Looper(
                [
                    rt.Dataset(data, batch_size=32),
                    module,
                    # Mid-run trace window: the capture leg of the
                    # measure->attribute loop (obs.prof parses it into
                    # obs/prof/* gauges when the window closes).
                    rt.Profiler(trace_start=4, trace_steps=3),
                    rt.Tracker(project="smoke", directory=runs_dir),
                ],
                tag="train", progress=False,
            )
        ],
        num_epochs=2,
        runtime=runtime,
    ).launch()

    scraping.set()
    poller.join(timeout=5)

    out_dir = os.path.join(runs_dir, "smoke")
    telemetry_path = os.path.join(out_dir, "telemetry.json")
    check(os.path.exists(telemetry_path), f"{telemetry_path} not written")
    with open(telemetry_path) as f:
        record = json.load(f)

    goodput = record["goodput"]
    total = goodput["total_wall_s"]
    cat_sum = sum(goodput["categories"].values())
    check(total > 0, "zero total wall-clock")
    check(
        abs(cat_sum - total) <= 0.05 * total,
        f"goodput categories sum {cat_sum:.4f}s != total {total:.4f}s",
    )
    check(goodput["categories"]["step"] > 0, "no step time accounted")
    check(goodput["categories"]["compile"] > 0, "no compile time accounted")

    spans_path = os.path.join(out_dir, record["spans"]["file"])
    events = load_chrome_trace(spans_path)
    complete = [e for e in events if e.get("ph") == "X"]
    check(len(complete) > 0, "span file has no complete spans")
    cats = {e.get("cat") for e in complete}
    check({"step", "compile", "data_wait", "flush"} <= cats,
          f"span categories incomplete: {sorted(cats)}")

    # Health sentinels ran on every step of this clean run: the decoded
    # gauges are present, nothing anomalous, nothing skipped.
    health = record.get("health")
    check(health is not None, "no health section in telemetry.json")
    check(health["anomalies"] == 0,
          f"clean run reported {health['anomalies']} anomalies")
    check(health["skipped_steps"] == 0,
          f"clean run skipped {health['skipped_steps']} steps")
    check(health["last_good_step"] is not None, "no health word decoded")
    gauges = record["metrics"]["gauges"]
    for key in ("health/grad_norm", "health/update_ratio",
                "health/last_good_step"):
        check(key in gauges, f"{key} missing from the registry snapshot")

    # obs/* scalars landed in the tracker backend stream.
    jsonl = os.path.join(runs_dir, "smoke.jsonl")
    with open(jsonl) as f:
        lines = [json.loads(line) for line in f if line.strip()]
    check(any(k.startswith("obs/") for rec in lines for k in rec),
          "no obs/* scalars in the tracker stream")
    check(any(k.startswith("health/") for rec in lines for k in rec),
          "no health/* scalars in the tracker stream")

    # The report CLI renders both files.
    for path in (telemetry_path, spans_path):
        proc = subprocess.run(
            [sys.executable, "-m", "rocket_tpu.obs", "report", path],
            capture_output=True, text=True,
        )
        check(proc.returncode == 0,
              f"report CLI failed on {path}: {proc.stderr[-300:]}")

    # -- capture -> parse -> reconcile (ISSUE 13) --------------------------
    # capture: the Profiler's window parsed into obs/prof/* gauges the
    # moment it closed (continuous measured attribution).
    check("obs/prof/measured_step_us" in gauges,
          "no obs/prof/* gauges — the trace window was not parsed")
    # The window opens/closes INSIDE the boundary waves' step
    # annotations (the Profiler capsule dispatches mid-wave), so of the
    # 3-step window the fully-interior annotations record: >= 2.
    check((gauges.get("obs/prof/n_steps") or 0) >= 2,
          f"obs/prof/n_steps {gauges.get('obs/prof/n_steps')}: trace "
          "window captured fewer than 2 annotated steps")
    report_out = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "report", telemetry_path],
        capture_output=True, text=True,
    ).stdout
    check("measured step attribution" in report_out,
          "report CLI missing the prof section")

    # parse: the prof CLI renders the captured window as a nonempty
    # per-op attribution table (exit contract: 0 = rendered).
    trace_dir = os.path.join(workdir, "traces")
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "prof", trace_dir],
        capture_output=True, text=True,
    )
    check(proc.returncode == 0,
          f"obs prof CLI failed on {trace_dir}: {proc.stderr[-300:]}")
    step_count = re.search(r"(\d+) annotated step\(s\)", proc.stdout)
    check(step_count is not None and int(step_count.group(1)) > 0,
          "obs prof saw no annotated steps")
    table_rows = [
        line for line in proc.stdout.splitlines()
        if line.strip() and not line.startswith(("trace:", "device",
                                                 "per step", "category",
                                                 " ", "op "))
    ]
    check(len(table_rows) > 0, "obs prof attribution table is empty")

    # reconcile: the calib CLI captures a fresh trace of the gpt2
    # sentinel step, joins it against the priced DAG and holds the
    # committed budget.
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.analysis", "calib",
         "--target", "gpt2_sentinel", "--budgets",
         os.path.join("tests", "fixtures", "budgets", "calib")],
        capture_output=True, text=True,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    )
    check(proc.returncode == 0,
          f"analysis calib gate failed: {proc.stdout[-300:]} "
          f"{proc.stderr[-300:]}")

    # -- live export: shards, /metrics, SLO gates (ISSUE 19) ---------------
    # Mid-run scrape: the poller caught at least one /metrics body, and
    # it carries the train families a Prometheus server would ingest.
    check(scrape["n"] > 0, "no successful mid-run /metrics scrape")
    for family in ("rocket_tpu_goodput_goodput_fraction",
                   "rocket_tpu_perf_steps_per_sec",
                   "rocket_tpu_obs_slo_seeded_grad_ceiling_burn_rate"):
        check(family in scrape["body"],
              f"{family} missing from the mid-run scrape")
    check('rank="0"' in scrape["body"], "scrape samples carry no rank label")

    # Streaming shard: one continuous per-rank history next to
    # telemetry.json (the early default-dir records migrated along when
    # the Tracker resolved runs/smoke), final record flagged.
    shard_path = os.path.join(out_dir, "telemetry", "rank0.jsonl")
    check(os.path.exists(shard_path), f"{shard_path} not written")
    with open(shard_path) as f:
        shard = [json.loads(line) for line in f if line.strip()]
    check(len(shard) >= 2, f"only {len(shard)} shard record(s)")
    check(shard[-1]["final"], "no final=True shard record at teardown")
    check(shard[-1]["seq"] == len(shard) - 1,
          "shard seq not contiguous — split or clobbered history")
    check(shard[-1]["hostname"] and shard[-1]["rank"] == 0,
          "shard records missing process identity")

    # Online detection: the seeded violation fired DURING the run — the
    # edge counter landed in the registry snapshot telemetry.json keeps.
    counters = record["metrics"]["counters"]
    check(counters.get("obs/slo/seeded_grad_ceiling/violations", 0) >= 1,
          "seeded SLO violation not detected online")
    check(gauges.get("obs/slo/seeded_grad_ceiling/violated") == 1.0,
          "obs/slo/*/violated gauge not set")

    # Offline gates over the same shards: violating spec -> exit 1 with
    # a VIOLATION line; slack spec -> exit 0; fleet view renders.
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "watch", out_dir,
         "--slo", violating_spec],
        capture_output=True, text=True,
    )
    check(proc.returncode == 1,
          f"obs watch on the seeded violation exited {proc.returncode} "
          f"(want 1): {proc.stderr[-300:]}")
    check("VIOLATION seeded_grad_ceiling" in proc.stdout,
          "obs watch printed no VIOLATION line")
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "watch", out_dir,
         "--slo", passing_spec],
        capture_output=True, text=True,
    )
    check(proc.returncode == 0,
          f"obs watch on the slack spec exited {proc.returncode} (want 0)")
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "top", out_dir, "--once"],
        capture_output=True, text=True,
    )
    check(proc.returncode == 0,
          f"obs top --once failed: {proc.stderr[-300:]}")
    check("1 rank(s)" in proc.stdout, "obs top did not render the fleet")

    print(
        "obs smoke OK: "
        f"goodput step={goodput['fractions']['step']:.1%} "
        f"compile={goodput['fractions']['compile']:.1%}, "
        f"{len(complete)} spans, health sentinels green "
        f"(last good step {health['last_good_step']}), strict guards "
        "green, capture->parse->reconcile leg green, live export green "
        f"({scrape['n']} mid-run scrapes, {len(shard)} shard records, "
        "seeded SLO gate fired)"
    )


if __name__ == "__main__":
    main()
