#!/usr/bin/env python
"""Serve smoke gate: the continuous-batching engine end to end on CPU.

Four legs (wired into scripts/check.sh and CI):

1. **In-process**: a 50-request synthetic workload on a tiny LM through
   :class:`rocket_tpu.serve.ServeEngine` must (a) complete every request,
   (b) compile the decode wave and the prefill chunk exactly ONCE — zero
   retraces across 50 admissions/evictions/refills, checked against the
   obs registry gauges, (c) produce greedy outputs token-identical to
   ``generate()`` for sampled spot-checks, and (d) leave a telemetry.json
   whose serve gauges + per-request spans tell the same story.
2. **Live export** (ISSUE 19): a serving session with the live plane
   armed must expose a mid-serve ``/metrics`` endpoint carrying the
   serve families, stream telemetry shards, detect a seeded ITL-p99 SLO
   violation online (``obs/slo/*`` counter), and gate ``python -m
   rocket_tpu.obs watch --slo`` offline (exit 1 seeded / 0 slack).
3. **Scanned waves** (ISSUE 11): the same model served with
   ``decode_waves_per_dispatch=4`` must produce greedy outputs
   BIT-IDENTICAL to the k=1 engine for an identical workload, with zero
   retraces, exactly ONE ``jax.device_get`` per dispatch of k waves
   (the tunnel amortization the k-wave ``lax.scan`` exists for), and a
   measured tokens-per-dispatch meaningfully above 1.
4. **CLI**: ``python -m rocket_tpu.serve`` as a subprocess (with a
   k-wave flag) must stream output, print the serve report, exit 0, and
   the ``report`` subcommand must render its telemetry.
5. **Timeline** (ISSUE 20): per-request tail forensics end to end — a
   starved pool preempts + resumes requests whose single timeline spans
   both residencies (eviction gap visible, phase durations summing to
   the measured wall time within 5%), the seeded ITL-p99 SLO violation
   names the window's tail exemplars in its flight anomaly, and
   ``python -m rocket_tpu.obs timeline`` renders the waterfalls from the
   persisted shards.

Exits non-zero on the first violated invariant.
"""

import json
import os
import subprocess
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402


def check(condition, message):
    if not condition:
        print(f"serve smoke FAILED: {message}", file=sys.stderr)
        sys.exit(1)


def engine_leg(out_dir: str) -> None:
    from rocket_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        generate,
    )
    from rocket_tpu.obs.telemetry import Telemetry
    from rocket_tpu.serve import ServeConfig, ServeEngine

    config = TransformerConfig(
        vocab_size=64, max_seq_len=64, dim=32, num_layers=2, num_heads=4,
        dropout=0.0,
    )
    model = TransformerLM(config)
    variables = jax.jit(model.init)(jax.random.key(0))

    telemetry = Telemetry(enabled=True, out_dir=out_dir)
    telemetry.start()
    engine = ServeEngine(
        model, variables["params"],
        ServeConfig(max_slots=4, block_len=4, prefill_chunk=4,
                    max_model_len=48, num_blocks=17),  # starved -> evictions
        telemetry=telemetry,
    )
    rng = np.random.default_rng(0)
    jobs = []
    for _ in range(50):
        plen = int(rng.integers(1, 14))
        maxnew = int(rng.integers(1, 10))
        prompt = rng.integers(0, 64, size=plen).astype(np.int32)
        rid = engine.submit(prompt, max_new_tokens=maxnew, temperature=0.0)
        jobs.append((rid, prompt, maxnew))
    engine.drain()
    report = engine.report()
    check(report["requests"]["completed"] == 50,
          f"completed {report['requests']}")
    check(report["compiled"]["decode_traces"] == 1,
          f"decode retraced: {report['compiled']}")
    check(report["compiled"]["prefill_traces"] == 1,
          f"prefill retraced: {report['compiled']}")
    check(report["tokens_per_sec"] and report["tokens_per_sec"] > 0,
          f"tokens_per_sec {report['tokens_per_sec']}")
    check(report["time_to_first_token_s"]["count"] == 50, "ttft count")

    # Greedy spot-checks against generate() (every 10th request).
    for rid, prompt, maxnew in jobs[::10]:
        ref = np.asarray(
            generate(model, variables, prompt[None, :], maxnew, temperature=0)
        )[0, len(prompt):]
        got = np.asarray(engine.result(rid).tokens, np.int32)
        check((got == ref).all(), f"request {rid}: {got} != {ref}")

    telemetry.flush()
    telemetry.close(write=False)

    tel_path = os.path.join(out_dir, "telemetry.json")
    check(os.path.exists(tel_path), f"{tel_path} missing")
    with open(tel_path, encoding="utf-8") as f:
        doc = json.load(f)
    gauges = doc["metrics"]["gauges"]
    for name, want in [
        ("serve/decode_traces", 1), ("serve/prefill_traces", 1),
        ("serve/requests_completed", 50),
    ]:
        check(gauges.get(name) == want,
              f"telemetry gauge {name} = {gauges.get(name)}, want {want}")
    check(gauges.get("serve/tokens_generated", 0) > 0, "no tokens gauge")
    check(gauges.get("serve/kv_pool_bytes") == engine.engine.spec.pool_bytes,
          "kv_pool_bytes gauge")
    with open(os.path.join(out_dir, "spans.trace.json"), encoding="utf-8") as f:
        spans = json.load(f)["traceEvents"]
    n_req_spans = sum(
        1 for e in spans if str(e.get("name", "")).startswith("serve/request[")
    )
    check(n_req_spans == 50, f"{n_req_spans} request spans, want 50")
    print(f"serve smoke: engine leg OK "
          f"(preemptions={report['requests']['preemptions']}, "
          f"tok/s={report['tokens_per_sec']:.0f})")


def export_leg(out_dir: str) -> None:
    """Live plane over a serving session (ISSUE 19): /metrics scrapeable
    mid-serve with the serve families, shards streamed, and a seeded
    ITL-p99 SLO violation (objective 1 ps — any real inter-token gap
    violates) detected online and gating ``obs watch`` offline.

    The seeded spec, not default:serve, keeps the verdict deterministic:
    the committed serve objectives are TPU roofline ceilings a CPU toy
    run sits nowhere near."""
    import urllib.request

    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.obs.export import ExportConfig
    from rocket_tpu.obs.telemetry import Telemetry
    from rocket_tpu.serve import ServeConfig, ServeEngine

    violating = os.path.join(out_dir, "slo_itl_tight.json")
    passing = os.path.join(out_dir, "slo_itl_slack.json")
    os.makedirs(out_dir, exist_ok=True)
    for path, objective in ((violating, 1e-12), (passing, 3600.0)):
        with open(path, "w", encoding="utf-8") as f:
            json.dump({"version": 1, "slos": [
                {"name": "seeded_itl_p99", "kind": "quantile",
                 "metric": "serve/itl_s", "quantile": 0.99,
                 "objective": objective},
            ]}, f)

    config = TransformerConfig(
        vocab_size=64, max_seq_len=64, dim=32, num_layers=2, num_heads=4,
        dropout=0.0,
    )
    model = TransformerLM(config)
    variables = jax.jit(model.init)(jax.random.key(0))
    telemetry = Telemetry(enabled=True, out_dir=out_dir)
    telemetry.start()
    telemetry.start_export(
        ExportConfig(enabled=True, interval_s=0.2, metrics_port=0,
                     slo_path=violating),
        default_dir=out_dir,
    )
    exporter = telemetry.exporter
    check(exporter is not None and exporter.server is not None,
          "export config did not mount the live plane")
    engine = ServeEngine(
        model, variables["params"],
        ServeConfig(max_slots=4, block_len=8, prefill_chunk=8,
                    max_model_len=48),
        telemetry=telemetry,
    )
    rng = np.random.default_rng(3)
    for _ in range(10):
        prompt = rng.integers(0, 64, size=int(rng.integers(1, 10)))
        engine.submit(prompt.astype(np.int32), max_new_tokens=6,
                      temperature=0.0)
    engine.drain()
    # One deterministic tick (the thread also ticks at 0.2s cadence):
    # the seeded quantile SLO sees the serve/itl_s histogram and fires.
    record = exporter.tick()
    verdict, = [s for s in record["slo"] if s["name"] == "seeded_itl_p99"]
    check(verdict["violated"],
          f"seeded ITL SLO not violated online: {verdict}")
    counters = telemetry.registry.snapshot()["counters"]
    check(counters.get("obs/slo/seeded_itl_p99/violations", 0) >= 1,
          "online violation did not land the obs/slo/* edge counter")

    # Mid-serve scrape: the serve families a Prometheus server would
    # ingest, with cumulative buckets and the rank label.
    url = f"http://127.0.0.1:{exporter.server.port}/metrics"
    with urllib.request.urlopen(url, timeout=5) as resp:
        body = resp.read().decode()
    for family in ("rocket_tpu_serve_ttft_s_bucket",
                   "rocket_tpu_serve_itl_s_count",
                   "rocket_tpu_serve_slots_active",
                   "rocket_tpu_obs_slo_seeded_itl_p99_burn_rate"):
        check(family in body, f"{family} missing from the /metrics scrape")
    check('le="+Inf"' in body, "no +Inf closing bucket in the exposition")

    telemetry.close(write=False)
    shard_path = os.path.join(out_dir, "telemetry", "rank0.jsonl")
    check(os.path.exists(shard_path), f"{shard_path} not written")

    # Offline gates over the shards this session just streamed.
    watch = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "watch", out_dir,
         "--slo", violating],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    check(watch.returncode == 1,
          f"obs watch on the seeded ITL violation exited {watch.returncode} "
          f"(want 1): {watch.stderr[-300:]}")
    check("VIOLATION seeded_itl_p99" in watch.stdout,
          "obs watch printed no VIOLATION line")
    watch = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "watch", out_dir,
         "--slo", passing],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    check(watch.returncode == 0,
          f"obs watch on the slack spec exited {watch.returncode} (want 0)")
    print("serve smoke: export leg OK (mid-serve /metrics scrape, "
          "seeded ITL-p99 SLO fired online + gated offline)")


def scan_leg() -> None:
    """k-wave scanned dispatch: greedy parity with k=1, one device_get
    per k waves, zero retraces."""
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.serve import ServeConfig, ServeEngine

    config = TransformerConfig(
        vocab_size=64, max_seq_len=64, dim=32, num_layers=2, num_heads=4,
        dropout=0.0,
    )
    model = TransformerLM(config)
    variables = jax.jit(model.init)(jax.random.key(0))

    def run(k):
        engine = ServeEngine(
            model, variables["params"],
            ServeConfig(max_slots=4, block_len=8, prefill_chunk=8,
                        max_model_len=48, decode_waves_per_dispatch=k),
        )
        rng = np.random.default_rng(7)
        rids = []
        for _ in range(20):
            plen = int(rng.integers(1, 12))
            maxnew = int(rng.integers(3, 14))
            prompt = rng.integers(0, 64, size=plen).astype(np.int32)
            rids.append(engine.submit(prompt, max_new_tokens=maxnew,
                                      temperature=0.0))
        engine.drain()
        return engine, rids

    base, base_rids = run(1)
    scan, scan_rids = run(4)
    for b_rid, s_rid in zip(base_rids, scan_rids):
        b = base.result(b_rid).tokens
        s = scan.result(s_rid).tokens
        check(b == s, f"k=4 diverged from k=1 on request {s_rid}: {s} != {b}")

    report = scan.report()
    check(report["requests"]["completed"] == 20, "scan leg completion")
    check(report["compiled"]["decode_traces"] == 1,
          f"scan leg retraced: {report['compiled']}")
    eng = scan.engine
    check(eng.device_gets == eng.decode_dispatches,
          f"device_gets {eng.device_gets} != dispatches "
          f"{eng.decode_dispatches} — more than one host sync per k-wave "
          "dispatch")
    check(eng.decode_waves == 4 * eng.decode_dispatches,
          f"waves {eng.decode_waves} != 4 * dispatches "
          f"{eng.decode_dispatches}")
    tpd = report["dispatch"]["tokens_per_dispatch"]
    check(tpd and tpd > 1.5,
          f"tokens_per_dispatch {tpd} — the scan is not amortizing the "
          "tunnel")
    # Identical greedy workload => identical token count, ~4x fewer syncs.
    check(base.engine.device_gets > 2 * eng.device_gets,
          f"k=4 device_gets {eng.device_gets} not materially below k=1's "
          f"{base.engine.device_gets}")
    print(f"serve smoke: scan leg OK (tokens/dispatch={tpd}, "
          f"device_gets {base.engine.device_gets} -> {eng.device_gets})")


def cli_leg(out_dir: str) -> None:
    env = dict(os.environ)
    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.serve", "--requests", "12",
         "--max-new-tokens", "8", "--max-slots", "4", "--block-len", "8",
         "--prefill-chunk", "8", "--waves-per-dispatch", "2",
         "--show", "1", "--out-dir", out_dir],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=600,
    )
    check(proc.returncode == 0,
          f"CLI exited {proc.returncode}:\n{proc.stdout}\n{proc.stderr}")
    check("--- request 0 ---" in proc.stdout, "no streamed output")
    check("serve_report" in proc.stdout, "no report on stdout")
    payload = json.loads(proc.stdout[proc.stdout.index("{"):])
    check(payload["serve_report"]["requests"]["completed"] == 12,
          "CLI report completion count")
    check(os.path.exists(os.path.join(out_dir, "telemetry.json")),
          "CLI telemetry.json missing")

    rep = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.serve", "report", out_dir],
        env=env, cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    check(rep.returncode == 0, f"report subcommand failed:\n{rep.stderr}")
    check("serve/decode_traces" in rep.stdout, "report missing trace gauge")
    print("serve smoke: CLI leg OK")


def timeline_leg(out_dir: str) -> None:
    """Per-request tail forensics (ISSUE 20): preempted+resumed
    waterfalls, the SLO-violation -> exemplar link, and the timeline
    CLI over the persisted shards."""
    from rocket_tpu.models.transformer import TransformerConfig, TransformerLM
    from rocket_tpu.obs.export import ExportConfig, TelemetryExporter
    from rocket_tpu.obs.flight import FlightRecorder
    from rocket_tpu.obs.telemetry import Telemetry
    from rocket_tpu.serve import ServeConfig, ServeEngine

    os.makedirs(out_dir, exist_ok=True)
    violating = os.path.join(out_dir, "slo_itl_tight.json")
    with open(violating, "w", encoding="utf-8") as f:
        json.dump({"version": 1, "slos": [
            {"name": "seeded_itl_p99", "kind": "quantile",
             "metric": "serve/itl_s", "quantile": 0.99,
             "objective": 1e-12},
        ]}, f)

    config = TransformerConfig(
        vocab_size=64, max_seq_len=64, dim=32, num_layers=2, num_heads=4,
        dropout=0.0,
    )
    model = TransformerLM(config)
    variables = jax.jit(model.init)(jax.random.key(0))
    telemetry = Telemetry(enabled=True, out_dir=out_dir)
    telemetry.start()
    telemetry.flight = FlightRecorder(telemetry=telemetry)
    engine = ServeEngine(
        model, variables["params"],
        # Starved pool (8 allocatable blocks, 4 slots): decode growth
        # exhausts it, so the youngest active request preempts and
        # resumes — the tail shape this leg exists to trace.
        ServeConfig(max_slots=4, block_len=4, prefill_chunk=4,
                    max_model_len=32, num_blocks=9),
        telemetry=telemetry,
    )
    # Warmup pays the two compiles, then the tracer window resets so the
    # measured waterfalls carry no compile time in their phases.
    for _ in range(2):
        engine.submit(np.asarray([1, 2], np.int32), max_new_tokens=2,
                      temperature=0.0)
    engine.drain()
    engine.tracer.flush(out_dir)

    rng = np.random.default_rng(3)
    rids = []
    for _ in range(8):
        prompt = rng.integers(0, 64, size=int(rng.integers(2, 7)))
        rids.append(engine.submit(prompt.astype(np.int32),
                                  max_new_tokens=int(rng.integers(10, 16)),
                                  temperature=0.0))
    engine.drain()
    preempted = [r for r in rids if engine.result(r).preemptions > 0]
    check(preempted, "starved pool produced no preemption to trace")

    # One synchronous exporter tick: flushes the measured window's
    # timelines + exemplars, then evaluates the seeded SLO against them.
    exporter = TelemetryExporter(
        telemetry, ExportConfig(enabled=True, slo_path=violating),
        identity={"rank": 0, "hostname": "smoke", "pid": os.getpid()},
        default_dir=out_dir,
    )
    record = exporter.tick()
    check(record["reqtrace"]["finished"] == 8,
          f"reqtrace window drained {record['reqtrace']} (want 8 finished)")
    verdict, = [s for s in record["slo"] if s["name"] == "seeded_itl_p99"]
    check(verdict["violated"], f"seeded ITL SLO not violated: {verdict}")
    exemplars = verdict.get("exemplars") or {}
    named = set(exemplars.get("itl_gap", [])) | set(exemplars.get("ttft", []))
    check(named, f"violation carries no exemplars: {verdict}")
    check(set(preempted) & named,
          f"preempted request(s) {preempted} not among the violation's "
          f"tail exemplars {exemplars}")
    anomaly = [a for a in telemetry.flight.anomalies()
               if a.get("kind") == "slo_violation"][-1]
    check(anomaly.get("exemplars") == exemplars,
          f"flight anomaly exemplars diverge: {anomaly}")
    telemetry.close(write=False)
    for name in ("reqtrace.jsonl", "exemplars.jsonl"):
        path = os.path.join(out_dir, "telemetry", name)
        check(os.path.exists(path), f"{path} not persisted")

    # The timeline CLI over the persisted shards: the preempted request's
    # waterfall shows the eviction gap, one timeline spanning BOTH
    # residencies, phases summing to the measured wall time within 5%.
    victim = preempted[0]
    cli = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "timeline", out_dir,
         "--request", str(victim), "--format", "json"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    check(cli.returncode == 0,
          f"obs timeline --request exited {cli.returncode}: {cli.stderr}")
    rec, = json.loads(cli.stdout)["requests"]
    kinds = [e["ev"] for e in rec["events"]]
    check("evict" in kinds, f"no evict event on the waterfall: {kinds}")
    check(any(e.get("resumed") for e in rec["events"]
              if e["ev"] == "admit"),
          "no resumed re-admission on the preempted timeline")
    check(rec["phases"]["preempted_s"] > 0, f"no eviction gap: {rec['phases']}")
    phase_sum = sum(rec["phases"].values())
    check(abs(phase_sum - rec["total_s"]) <= 0.05 * rec["total_s"],
          f"phases {phase_sum} vs wall {rec['total_s']} beyond 5%")

    slowest = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "timeline", out_dir,
         "--slowest", "3"],
        cwd=REPO, capture_output=True, text=True, timeout=120,
    )
    check(slowest.returncode == 0,
          f"obs timeline --slowest exited {slowest.returncode}: "
          f"{slowest.stderr}")
    check("aggregate" in slowest.stdout, "no aggregate phase breakdown")
    print(f"serve smoke: timeline leg OK (preempted {preempted} traced, "
          f"exemplars {exemplars})")


def main() -> None:
    repo_runs = os.path.join(REPO, "runs")
    os.makedirs(repo_runs, exist_ok=True)
    import tempfile

    workdir = tempfile.mkdtemp(prefix="serve_smoke_", dir=repo_runs)
    engine_leg(os.path.join(workdir, "engine"))
    export_leg(os.path.join(workdir, "export"))
    scan_leg()
    cli_leg(os.path.join(workdir, "cli"))
    timeline_leg(os.path.join(workdir, "timeline"))
    print("serve smoke: all checks passed")


if __name__ == "__main__":
    main()
