"""Measure KV-cache decode throughput (GPT-2 124M) against its HBM roofline.

The generation loop is ONE compiled ``lax.fori_loop`` (``_generate_fn``,
models/transformer.py) — per-token dispatch latency CANNOT be the binding
term (one dispatch covers the whole generation). What binds a batch-8
decode step is HBM streaming:

* parameters: every layer's weights are read once per token step
  (~248 MB bf16 for 124M params after the f32->bf16 hoist at loop entry);
* KV caches: each step reads the full T_max cache per layer
  (B * Hkv * T_max * D * 2 dtypes * L);
* the head projection (tied wte, 50257 x 768) is part of the params.

Marginal ms/token is measured by generating at TWO lengths and dividing
the extra wall time by the extra tokens — prefill, dispatch, and sampling
setup cancel out.

Run on the real TPU: ``python scripts/profile_decode.py [--batch 8]``.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import numpy as np


def main():
    batches = [8, 32]
    for i, a in enumerate(sys.argv):
        if a == "--batch":
            if i + 1 >= len(sys.argv):
                raise SystemExit("usage: profile_decode.py [--batch N]")
            batches = [int(sys.argv[i + 1])]

    from rocket_tpu.models.transformer import (
        TransformerConfig,
        TransformerLM,
        generate,
    )

    config = TransformerConfig.gpt2_124m(max_seq_len=512)
    config.dropout = 0.0
    model = TransformerLM(config)
    variables = model.init(jax.random.key(0))
    n_params = model.num_params(variables)
    param_bytes = n_params * 2  # bf16 after the loop-entry cast

    rng = np.random.default_rng(0)
    for b in batches:
        prompt = rng.integers(0, config.vocab_size, size=(b, 16)).astype(np.int32)
        t_max = config.max_seq_len
        cache_bytes = (
            2 * b * config.num_heads * t_max
            * (config.dim // config.num_heads) * 2 * config.num_layers
        )
        floor_ms = (param_bytes + cache_bytes) / 819e9 * 1e3  # v5e ~819 GB/s

        def run(n):
            out = generate(
                model, variables, prompt, n, temperature=0.0,
            )
            np.asarray(out)  # true sync
            return out

        short, long_ = 64, 64 + 256
        run(short)  # compile both windows
        run(long_)
        t0 = time.perf_counter()
        run(short)
        t1 = time.perf_counter()
        run(long_)
        t2 = time.perf_counter()
        ms_tok = ((t2 - t1) - (t1 - t0)) / (long_ - short) * 1e3
        print(
            f"B={b}: {ms_tok:.3f} ms/token marginal "
            f"({b / ms_tok * 1e3:.0f} tok/s), HBM floor ~{floor_ms:.3f} ms "
            f"(params {param_bytes / 1e6:.0f} MB + caches "
            f"{cache_bytes / 1e6:.0f} MB @ 819 GB/s) "
            f"-> {floor_ms / ms_tok:.0%} of roofline",
            flush=True,
        )


if __name__ == "__main__":
    main()
