"""Decompose the ResNet-50 train step (BASELINE configs[3]) on one chip.

Round-2 left the conv path at 14-17% MFU with no trace on record; the
round-3 ask is >= 25% or a documented XLA-conv ceiling. This script times
the full fused step and isolated pieces (fwd only, fwd+bwd, stem alone) and
captures a ``jax.profiler`` trace whose per-op durations it summarizes
(CAVEAT from SURVEY §6: summed op durations are NOT wall time — use them to
rank sinks, never to claim speedups).

Run on the real TPU: ``python scripts/profile_resnet50.py [--trace]``.
"""

import glob
import gzip
import json
import sys
import time
from collections import defaultdict
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np


def sync(tree):
    leaves = [l for l in jax.tree.leaves(tree) if isinstance(l, jax.Array)]
    s = sum(jnp.sum(jnp.asarray(l, jnp.float32)) for l in leaves)
    return float(s)


def timeit(fn, *args, iters=10, warmup=2):
    out = None
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / iters


def main():
    B = 128  # the measured throughput knee (docs/performance.md)
    for i, a in enumerate(sys.argv):
        if a == "--batch":
            if i + 1 >= len(sys.argv):
                raise SystemExit(
                    "usage: profile_resnet50.py [--batch N] [--trace]"
                )
            B = int(sys.argv[i + 1])
    trace = "--trace" in sys.argv

    import rocket_tpu as rt
    from rocket_tpu import optim
    from rocket_tpu.core.module import Module
    from rocket_tpu.models.resnet import resnet50
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(seed=0)
    model = resnet50(num_classes=1000)

    def objective(b):
        import optax

        return optax.softmax_cross_entropy_with_integer_labels(
            b["logits"], b["label"]
        ).mean()

    module = Module(
        model,
        capsules=[
            rt.Loss(objective),
            rt.Optimizer(optim.momentum(beta=0.9), learning_rate=0.1),
        ],
        compute_dtype=jnp.bfloat16,
        runtime=runtime,
    )
    module.setup()
    rng = np.random.default_rng(0)
    batch = {
        "image": jax.device_put(
            rng.normal(size=(B, 224, 224, 3)).astype(np.float32)
        ),
        "label": jax.device_put(rng.integers(0, 1000, B).astype(np.int32)),
    }

    state = module.prepared.state
    step = module._train_step

    # The step donates its state arg — thread it through the timing loop.
    def run_steps(n):
        nonlocal state
        metrics = None
        for _ in range(n):
            state, metrics = step(state, batch)
        return metrics

    run_steps(2)
    sync(run_steps(1)["loss"])
    t0 = time.perf_counter()
    metrics = run_steps(12)
    sync(metrics["loss"])
    t_step = (time.perf_counter() - t0) / 12
    flops = 3 * 2 * 4.1e9 * B  # fwd+bwd ~3x fwd MACs, 2 FLOPs/MAC
    peak = 197e12
    print(f"full step: {t_step*1e3:.1f} ms  -> {B/t_step:.0f} img/s, "
          f"MFU {flops/t_step/peak:.3f}")

    # Forward only (eval step, same shapes, no BN-update difference in cost)
    eval_step = module._eval_step
    t_fwd = timeit(
        lambda: eval_step(state["params"], state["model_state"], batch)["logits"],
        iters=12,
    )
    print(f"fwd only:  {t_fwd*1e3:.1f} ms  ({t_fwd/t_step:.0%} of step)")

    if trace:
        tdir = "traces/resnet50"
        with jax.profiler.trace(tdir):
            metrics = run_steps(3)
            sync(metrics["loss"])
        # Find the trace.json.gz written by the profiler and rank op time.
        files = sorted(glob.glob(f"{tdir}/**/*.trace.json.gz", recursive=True))
        if not files:
            print("no trace file found")
            return
        with gzip.open(files[-1], "rt") as f:
            events = json.load(f).get("traceEvents", [])
        by_name = defaultdict(float)
        for e in events:
            if e.get("ph") == "X" and e.get("dur") and "args" in e:
                # TensorCore op rows carry 'long_name'/'name'
                name = e.get("name", "?")
                by_name[name] += e["dur"]
        total = sum(by_name.values())
        print(f"\ntop ops by summed duration (3 steps, total {total/1e3:.1f} ms):")
        for name, dur in sorted(by_name.items(), key=lambda kv: -kv[1])[:30]:
            print(f"  {dur/1e3:9.2f} ms  {dur/total:5.1%}  {name[:100]}")


if __name__ == "__main__":
    main()
