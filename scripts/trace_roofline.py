"""Per-op roofline table from a ``jax.profiler`` trace (TPU).

Each TensorCore event in the trace carries ``model_flops``,
``bytes_accessed`` and ``hlo_category`` — enough to compute, per HLO op,
what fraction of the MXU peak and of HBM bandwidth it achieved and which
resource binds it. This turns "the conv path is ~25% MFU" into a table
naming WHERE the other 75% goes (round-3 verdict ask #1).

CAVEAT (SURVEY §6): summed op durations are NOT wall time — gaps between
ops (scheduling, infeed) are invisible here. The table attributes the
measured on-device time; the bench's wall-clock MFU is the honest
end-to-end number.

Usage: ``python scripts/trace_roofline.py <trace_dir> [--peak-tflops 197]
[--peak-gbps 819] [--by source|category|op] [--steps N]``
"""

import glob
import gzip
import json
import sys
from collections import defaultdict


def load_events(trace_dir):
    files = sorted(
        glob.glob(f"{trace_dir}/**/*.trace.json.gz", recursive=True)
    )
    if not files:
        raise SystemExit(f"no *.trace.json.gz under {trace_dir}")
    with gzip.open(files[-1], "rt") as f:
        return json.load(f).get("traceEvents", [])


def main():
    args = sys.argv[1:]
    trace_dir = args[0] if args and not args[0].startswith("--") else "traces"
    peak_tflops, peak_gbps, by, steps = 197.0, 819.0, "source", 3
    flags = {"--peak-tflops", "--peak-gbps", "--by", "--steps"}
    for i, a in enumerate(args):
        if a in flags and i + 1 >= len(args):
            raise SystemExit(f"{a} needs a value")
        if a == "--peak-tflops":
            peak_tflops = float(args[i + 1])
        elif a == "--peak-gbps":
            peak_gbps = float(args[i + 1])
        elif a == "--by":
            by = args[i + 1]
        elif a == "--steps":
            steps = int(args[i + 1])

    rows = defaultdict(lambda: [0.0, 0.0, 0.0, set()])  # dur, flops, bytes, cats
    total = 0.0
    for e in load_events(trace_dir):
        if e.get("ph") != "X" or not e.get("dur"):
            continue
        a = e.get("args") or {}
        cat = a.get("hlo_category")
        if cat is None:
            continue  # outer jit rows, host rows
        dur_s = float(a.get("device_duration_ps", 0)) * 1e-12
        if dur_s == 0:
            continue
        if by == "source":
            src = a.get("source", "?")
            key = f"{src} [{cat}]"
        elif by == "category":
            key = cat
        else:
            key = e.get("name", "?")
        r = rows[key]
        r[0] += dur_s
        r[1] += float(a.get("model_flops", 0) or 0)
        r[2] += float(a.get("bytes_accessed", 0) or 0)
        r[3].add(cat)
        total += dur_s

    print(
        f"{'time/step':>10} {'%step':>6} {'TFLOP/s':>8} {'%MXU':>6} "
        f"{'GB/s':>7} {'%HBM':>6}  binder  key"
    )
    for key, (dur, flops, nbytes, cats) in sorted(
        rows.items(), key=lambda kv: -kv[1][0]
    )[:25]:
        tf = flops / dur / 1e12
        gbs = nbytes / dur / 1e9
        mxu = tf / peak_tflops
        hbm = gbs / peak_gbps
        binder = "MXU" if mxu >= hbm else "HBM"
        if max(mxu, hbm) < 0.15:
            binder = "neither(!)"
        print(
            f"{dur / steps * 1e3:9.2f}ms {dur / total:6.1%} {tf:8.1f} "
            f"{mxu:6.1%} {gbs:7.0f} {hbm:6.1%}  {binder:10s}  {key[:90]}"
        )
    print(f"\nsummed device time/step: {total / steps * 1e3:.1f} ms "
          f"(over {steps} steps; gaps not included)")


if __name__ == "__main__":
    main()
