#!/usr/bin/env python
"""Structural-kernel-search smoke: the generate-and-verify loop, CPU-only.

Proves ISSUE 14's acceptance spine without hardware, in four legs:

1. **enumerate -> verify**: the CPU smoke sweeps of the structural
   TuneSpaces (``fused_conv``, ``block_attn`` — interpret mode) must
   enumerate their variant candidates and pass fwd+bwd parity on EVERY
   one: no candidate errors, no rejections among the shipped variants.
2. **table round-trip**: a structural winner written to a table must
   resolve through the runtime lookup for its (device kind, bucket,
   dtype) key, validate clean against the TuneSpace, and surface in
   ``tables_summary``'s ``structural_wins``.
3. **seeded-bad rejection** (the true-positive leg the whole PR rests
   on): a deliberately wrong-but-fast fake variant registered in a
   test-only TuneSpace must be REJECTED by the sweep's parity gate —
   never timed into the ranking, never a winner.
4. **stale structural winner**: a table entry pinning a variant that no
   longer exists in its TuneSpace must fail ``validate_tables`` loudly.

Exit non-zero on the first failing leg (CI wiring: scripts/check.sh).
"""

import os
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


def fail(msg: str) -> None:
    print(f"tune_structural_smoke: FAIL — {msg}", file=sys.stderr)
    sys.exit(1)


def leg_enumerate_verify():
    from rocket_tpu.tune.tuner import load_cases, sweep_case

    for name in ("fused_conv/smoke", "block_attn/smoke"):
        case = load_cases()[name]
        report = sweep_case(case, iters=1, log=lambda s: None)
        if not report.results:
            fail(f"{name}: no candidates enumerated")
        impls = {r.config.get("impl") for r in report.results}
        if impls == {"reference"}:
            fail(f"{name}: no structural variant enumerated")
        for r in report.results:
            if r.error is not None:
                fail(f"{name}: candidate {r.config} errored: {r.error}")
            if not r.parity_ok:
                fail(f"{name}: candidate {r.config} failed parity "
                     f"(err={r.max_err:.3g}) — a shipped variant must be "
                     "numerically faithful")
        print(f"tune_structural_smoke: {name} — "
              f"{len(report.results)} candidates enumerated, all "
              "parity-clean")


def leg_table_round_trip():
    import jax.numpy as jnp

    from rocket_tpu import tune
    from rocket_tpu.tune.space import TUNE_SPACES

    shape = {"b": 64, "t": 256, "d": 256, "h": 4}
    space = TUNE_SPACES["block_attn"]
    entry = {
        "device_kind": "TPU v5 lite",
        "dtype": "bfloat16",
        "shape": shape,
        "shape_bucket": space.bucket(shape),
        "config": {"impl": "fused", "epilogue": "fused", "block_b": 2},
        "speedup": 1.31,
    }
    with tempfile.TemporaryDirectory() as tmp:
        os.environ["ROCKET_TPU_TUNE_DIR"] = tmp
        tune.reset_table_cache()
        try:
            for kernel in TUNE_SPACES:
                tune.write_table(kernel, [entry] if kernel == "block_attn"
                                 else [])
            problems = tune.validate_tables(tmp)
            if problems:
                fail(f"round-trip table did not validate: {problems}")
            with tune.priced_device_kind("TPU v5 lite"):
                hit = tune.get_config("block_attn", shape=shape,
                                      dtype=jnp.bfloat16)
            if hit != entry["config"]:
                fail(f"lookup returned {hit!r}, wanted the structural "
                     f"winner {entry['config']!r}")
            summary = tune.tables_summary(tmp)
            wins = summary["structural_wins"]
            if not any(w["kernel"] == "block_attn"
                       and w["variant"].get("impl") == "fused"
                       for w in wins):
                fail(f"structural win missing from tables_summary: {wins}")
        finally:
            del os.environ["ROCKET_TPU_TUNE_DIR"]
            tune.reset_table_cache()
    print("tune_structural_smoke: table round-trip — structural winner "
          "resolves, validates, and surfaces in structural_wins")


def leg_seeded_bad_rejection():
    import jax.numpy as jnp
    import numpy as np

    from rocket_tpu.tune.space import TUNE_SPACES, TuneSpace
    from rocket_tpu.tune.tuner import TuneCase, sweep_case

    space = TuneSpace(
        kernel="smoke_fake",
        axes={"impl": ("reference", "wrongfast")},
        shape_keys=("n",),
        default=lambda shape: {"impl": "reference"},
        structural=("impl",),
        doc="test-only: 'wrongfast' returns a scaled (wrong) output "
            "instantly — the parity gate must discard it",
    )
    TUNE_SPACES[space.kernel] = space
    try:
        x = jnp.asarray(np.linspace(0.0, 1.0, 256, dtype=np.float32))

        def build():
            def run(config):
                if (config or {}).get("impl") == "wrongfast":
                    return x * 1.5  # fast AND wrong
                return x
            return run

        case = TuneCase(name="fake/seeded_bad", kernel="smoke_fake",
                        shape={"n": 256}, dtype="float32", build=build)
        report = sweep_case(case, iters=1, min_speedup=1.0)
        bad = [r for r in report.results
               if r.config == {"impl": "wrongfast"}]
        if not bad:
            fail("wrongfast variant was never enumerated")
        if bad[0].parity_ok:
            fail("wrongfast variant PASSED parity — the rejection gate "
                 "is broken")
        if bad[0].mean_us is not None:
            fail("wrongfast variant was timed — rejection must precede "
                 "ranking")
        if report.winner is not None:
            fail(f"sweep crowned a winner {report.winner.config!r} from "
                 "a wrong variant")
    finally:
        del TUNE_SPACES[space.kernel]
    print("tune_structural_smoke: seeded-bad — wrong-but-fast variant "
          "rejected by the parity gate before timing")


def leg_stale_structural_winner():
    from rocket_tpu import tune
    from rocket_tpu.tune.space import TUNE_SPACES

    shape = {"n": 262144, "c": 64}
    with tempfile.TemporaryDirectory() as tmp:
        for kernel in TUNE_SPACES:
            tune.write_table(kernel, [{
                "device_kind": "TPU v5 lite",
                "dtype": "bfloat16",
                "shape": shape,
                "shape_bucket": TUNE_SPACES["fused_conv"].bucket(shape),
                "config": {"impl": "retired_variant",
                           "schedule": "twopass", "block_rows": 512},
            }] if kernel == "fused_conv" else [], configs_dir=tmp)
        problems = tune.validate_tables(tmp)
        stale = [p for p in problems if "stale structural winner" in p]
        if not stale:
            fail(f"retired variant not flagged as stale: {problems}")
    print("tune_structural_smoke: stale structural winner — retired "
          "variant fails the table gate loudly")


LEGS = {
    "enumerate": leg_enumerate_verify,
    "roundtrip": leg_table_round_trip,
    "seeded-bad": leg_seeded_bad_rejection,
    "stale": leg_stale_structural_winner,
}


def main(argv=None) -> int:
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument(
        "--leg", choices=sorted(LEGS), default=None,
        help="run ONE leg standalone (CI attribution steps); default "
             "runs all four",
    )
    args = parser.parse_args(argv)
    if args.leg:
        LEGS[args.leg]()
    else:
        for leg in ("enumerate", "roundtrip", "seeded-bad", "stale"):
            LEGS[leg]()
    print("tune_structural_smoke: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
