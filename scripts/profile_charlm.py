"""Decompose the char-LM train step (BASELINE configs[2]) on one chip.

Round-4 left char-LM at ~25-27% MFU with a paragraph ("~50 kernels,
small matmuls and per-op overheads") where ResNet-50 got a trace-backed
roofline table — the round-5 ask is the same discipline here: capture a
``jax.profiler`` trace of the full fused step and attribute the
on-device time per op (then feed the trace dir to
``scripts/trace_roofline.py``).

Also times the fused step at several batch sizes and with the candidate
fusion levers, so "attack or prove the ceiling" decisions ride measured
wall-clock (summed op durations are NOT wall time — SURVEY §6).

Run on the real TPU: ``python scripts/profile_charlm.py [--trace]
[--batch N] [--config k=v ...]``.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np

V5E_BF16_PEAK = 197e12


def sync(x):
    return float(jnp.asarray(x, jnp.float32))


def main():
    B = 128
    trace = "--trace" in sys.argv
    overrides = {}
    for i, a in enumerate(sys.argv):
        if a == "--batch":
            B = int(sys.argv[i + 1])
        if a == "--config":
            for kv in sys.argv[i + 1:]:
                if "=" not in kv:
                    break
                k, v = kv.split("=", 1)
                overrides[k] = eval(v)  # noqa: S307 — operator tool

    import rocket_tpu as rt
    from rocket_tpu import optim
    from rocket_tpu.core.module import Module
    from rocket_tpu.data.text import CharTokenizer, synthetic_corpus
    from rocket_tpu.models.transformer import (
        TransformerConfig, TransformerLM, next_token_loss,
    )
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(seed=0)
    tok = CharTokenizer(synthetic_corpus(10_000))
    config = TransformerConfig.char_lm(
        vocab_size=tok.vocab_size, max_seq_len=256
    )
    config.dropout = 0.0
    for k, v in overrides.items():
        setattr(config, k, v)
    T, D, L = config.max_seq_len, config.dim, config.num_layers
    model = TransformerLM(config)
    module = Module(
        model,
        capsules=[rt.Loss(next_token_loss()),
                  rt.Optimizer(optim.adamw(), learning_rate=3e-4)],
        compute_dtype=jnp.bfloat16,
        runtime=runtime,
    )
    module.setup()
    tokens = np.random.default_rng(0).integers(
        0, config.vocab_size, (B, T)).astype(np.int32)
    batch = {"tokens": jax.device_put(tokens)}
    state = module.prepared.state
    step = module._train_step

    def run(n, state):
        for _ in range(n):
            state, metrics = step(state, batch)
        return state, metrics

    state, metrics = run(5, state)
    sync(metrics["loss"])
    iters = 60
    t0 = time.perf_counter()
    state, metrics = run(iters, state)
    sync(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters
    n_params = sum(int(l.size) for l in jax.tree.leaves(state["params"]))
    flops_per_tok = 6 * n_params + 12 * L * T * D
    tok_s = B * T / dt
    print(f"B={B} cfg={overrides}: {dt*1e3:.3f} ms/step  {tok_s:,.0f} tok/s  "
          f"MFU={tok_s*flops_per_tok/V5E_BF16_PEAK:.1%}  "
          f"({n_params/1e6:.2f}M params)")

    if trace:
        tdir = "traces/charlm"
        with jax.profiler.trace(tdir):
            state, metrics = run(3, state)
            sync(metrics["loss"])
        print(f"trace written to {tdir} — summarize with "
              f"python scripts/trace_roofline.py {tdir}")


if __name__ == "__main__":
    main()
