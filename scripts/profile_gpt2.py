"""Decompose GPT-2 124M single-chip step time to target MFU work.

Times the full fused train step and isolated pieces (attention fwd+bwd,
logits+loss fwd+bwd, one MLP matmul) so optimization effort lands where the
time actually is. Run on the real TPU chip: ``python scripts/profile_gpt2.py``.

NOTE (axon tunnel): ``jax.block_until_ready`` returns immediately on this
platform — only an actual host fetch synchronizes. All timings here sync by
fetching a scalar reduced from the result.
"""

import sys
import time
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

import jax
import jax.numpy as jnp
import numpy as np
import optax

V5E_BF16_PEAK = 197e12


def sync(tree):
    """True device sync: fetch one scalar that depends on every leaf."""
    leaves = [l for l in jax.tree.leaves(tree) if isinstance(l, jax.Array)]
    s = sum(jnp.sum(jnp.asarray(l, jnp.float32)) for l in leaves)
    return float(s)


def timeit(fn, *args, iters=20, warmup=3):
    for _ in range(warmup):
        out = fn(*args)
    sync(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    sync(out)
    return (time.perf_counter() - t0) / iters


def main():
    B, T, D, H, V, L = 8, 1024, 768, 12, 50257, 12
    key = jax.random.key(0)

    # --- full train step through the framework ---------------------------
    import rocket_tpu as rt
    from rocket_tpu import optim
    from rocket_tpu.core.module import Module
    from rocket_tpu.models.transformer import (
        TransformerConfig, TransformerLM, next_token_loss,
    )
    from rocket_tpu.runtime.context import Runtime

    runtime = Runtime(seed=0)
    config = TransformerConfig.gpt2_124m()
    model = TransformerLM(config)
    module = Module(
        model,
        capsules=[rt.Loss(next_token_loss()), rt.Optimizer(optim.adamw(), learning_rate=3e-4)],
        compute_dtype=jnp.bfloat16,
        runtime=runtime,
    )
    module.setup()
    tokens = np.random.default_rng(0).integers(0, V, (B, T)).astype(np.int32)
    batch = {"tokens": jax.device_put(tokens)}

    state = module.prepared.state
    step = module._train_step

    for _ in range(3):
        state, metrics = step(state, batch)
    sync(metrics["loss"])
    iters = 20
    t0 = time.perf_counter()
    for _ in range(iters):
        state, metrics = step(state, batch)
    sync(metrics["loss"])
    dt = (time.perf_counter() - t0) / iters
    tok_s = B * T / dt
    flops = 6 * 124e6 * B * T + 12 * L * B * T * T * D
    print(f"full train step: {dt*1e3:.2f} ms  {tok_s:,.0f} tok/s  "
          f"~{flops/dt/1e12:.1f} TFLOP/s  MFU={flops/dt/V5E_BF16_PEAK:.1%}")

    # --- attention fwd+bwd -------------------------------------------------
    from rocket_tpu.nn.attention import dot_product_attention

    q = jax.random.normal(key, (B, H, T, D // H), jnp.bfloat16)
    k2 = jax.random.normal(key, (B, H, T, D // H), jnp.bfloat16)
    v2 = jax.random.normal(key, (B, H, T, D // H), jnp.bfloat16)

    @jax.jit
    def attn_fwd(q, k, v):
        return dot_product_attention(q, k, v, causal=True)

    @jax.jit
    def attn_bwd(q, k, v):
        return jax.grad(
            lambda q, k, v: dot_product_attention(q, k, v, causal=True)
            .astype(jnp.float32).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)

    dt_f = timeit(attn_fwd, q, k2, v2)
    dt_b = timeit(attn_bwd, q, k2, v2)
    attn_flops = 4 * B * H * T * T * (D // H)
    print(f"attention fwd: {dt_f*1e3:.2f} ms ({attn_flops/dt_f/1e12:.1f} TFLOP/s eff)  "
          f"bwd+fwd: {dt_b*1e3:.2f} ms; x{L} layers = {L*(dt_f+dt_b)*1e3:.1f} ms")

    # --- logits + loss fwd+bwd --------------------------------------------
    x = jax.random.normal(key, (B, T, D), jnp.bfloat16)
    wte = jax.random.normal(key, (V, D), jnp.float32)
    targets = jnp.asarray(tokens)

    @jax.jit
    def loss_fn(x, wte):
        logits = jnp.einsum("btd,vd->btv", x, wte.astype(x.dtype),
                            preferred_element_type=jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            logits[:, :-1].astype(jnp.float32), targets[:, 1:]
        ).mean()

    @jax.jit
    def loss_bwd(x, wte):
        return jax.grad(loss_fn, argnums=(0, 1))(x, wte)

    dt_lf = timeit(loss_fn, x, wte)
    dt_lb = timeit(loss_bwd, x, wte)
    logit_flops = 2 * B * T * D * V
    print(f"logits+loss fwd (FULL, not what gpt2_124m runs): "
          f"{dt_lf*1e3:.2f} ms ({logit_flops/dt_lf/1e12:.1f} TFLOP/s)  "
          f"fwd+bwd: {dt_lb*1e3:.2f} ms ({3*logit_flops/dt_lb/1e12:.1f} TFLOP/s)")

    # --- chunked head+CE (loss_chunk — the production gpt2_124m path) ------
    from rocket_tpu.models.transformer import _chunked_next_token_nll

    @jax.jit
    def chunked_fn(x, wte):
        return _chunked_next_token_nll(
            x, targets, 128,
            lambda xc: jnp.einsum("bcd,vd->bcv", xc, wte.astype(xc.dtype)),
        )

    @jax.jit
    def chunked_bwd(x, wte):
        return jax.grad(chunked_fn, argnums=(0, 1))(x, wte)

    dt_cf = timeit(chunked_fn, x, wte)
    dt_cb = timeit(chunked_bwd, x, wte)
    print(f"chunked head+CE fwd: {dt_cf*1e3:.2f} ms "
          f"({logit_flops/dt_cf/1e12:.1f} TFLOP/s)  "
          f"fwd+bwd: {dt_cb*1e3:.2f} ms "
          f"({3*logit_flops/dt_cb/1e12:.1f} TFLOP/s model-flops)")

    # --- one MLP matmul pair ----------------------------------------------
    w1 = jax.random.normal(key, (D, 4 * D), jnp.bfloat16)
    w2 = jax.random.normal(key, (4 * D, D), jnp.bfloat16)

    @jax.jit
    def mlp(x, w1, w2):
        return jax.nn.gelu(x @ w1) @ w2

    dt_m = timeit(mlp, x, w1, w2)
    mlp_flops = 2 * B * T * D * 4 * D * 2
    print(f"mlp fwd: {dt_m*1e3:.2f} ms ({mlp_flops/dt_m/1e12:.1f} TFLOP/s eff); "
          f"x{L} = {L*dt_m*1e3:.1f} ms fwd only")


if __name__ == "__main__":
    main()
