#!/usr/bin/env python
"""Blackbox smoke gate: an injected-NaN batch must leave a usable trail.

Two legs over the same poisoned dataset (one batch of all-NaN images),
both under ``Runtime(strict=True)``:

* ``anomaly_action="skip_step"`` — the run finishes, every final param is
  finite, and the skip is counted in the health summary;
* ``anomaly_action="dump_and_halt"`` — the run halts with
  ``HealthAnomalyError`` and a complete ``blackbox/`` bundle exists
  (manifest + anomaly timeline + emergency checkpoint) that
  ``python -m rocket_tpu.obs blackbox`` renders.

Exits non-zero on the first violated invariant (wired into
scripts/check.sh and CI).
"""

import glob
import json
import os
import subprocess
import sys
import tempfile

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import optax  # noqa: E402

import rocket_tpu as rt  # noqa: E402
from rocket_tpu import optim  # noqa: E402
from rocket_tpu.models.mlp import MLP  # noqa: E402
from rocket_tpu.obs import HealthAnomalyError  # noqa: E402


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


def check(condition, message):
    if not condition:
        print(f"blackbox smoke FAILED: {message}", file=sys.stderr)
        sys.exit(1)


def poisoned_data(n=128, nan_from=64, nan_to=72):
    rng = np.random.default_rng(0)
    data = []
    for i in range(n):
        image = rng.normal(size=8).astype(np.float32)
        if nan_from <= i < nan_to:
            image[:] = np.nan  # one poisoned batch (batch_size=32 -> batch 2)
        data.append({"image": image, "label": np.int32(i % 4)})
    return data


class GrabParams(rt.Capsule):
    """Keeps a reference to the module's latest params so their
    finiteness can be asserted after DESTROY tears the tree down."""

    def __init__(self, module):
        super().__init__(priority=10)
        self._module = module
        self.params = None

    def launch(self, attrs=None):
        if self._module.state is not None:
            self.params = self._module.state["params"]


def run(workdir, action, with_checkpointer):
    runtime = rt.Runtime(
        mesh_shape={"data": 8}, seed=0, project_dir=workdir,
        strict=True, health=True, anomaly_action=action,
    )
    module = rt.Module(
        MLP(in_features=8, num_classes=4, hidden=(16,)),
        capsules=[rt.Loss(cross_entropy),
                  rt.Optimizer(optim.adam(), learning_rate=1e-2)],
    )
    grab = GrabParams(module)
    capsules = [rt.Dataset(poisoned_data(), batch_size=32), module, grab]
    if with_checkpointer:
        capsules.append(
            rt.Checkpointer(output_dir=os.path.join(workdir, "ckpt"),
                            save_every=10_000)
        )
    launcher = rt.Launcher(
        [rt.Looper(capsules, tag="train", progress=False)],
        num_epochs=2, runtime=runtime,
    )
    return runtime, grab, launcher


def _workdir(prefix):
    # Under the repo's (gitignored) runs/ — NOT the system tmpdir — so a
    # failing CI run's telemetry + blackbox bundles land inside the
    # workspace where the runs/** artifact-upload step can find them.
    repo_runs = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "runs"
    )
    os.makedirs(repo_runs, exist_ok=True)
    return tempfile.mkdtemp(prefix=prefix, dir=repo_runs)


def main() -> None:
    # Leg 1: skip_step — the poisoned batch is survived, state stays finite.
    workdir = _workdir("blackbox_skip_")
    runtime, grab, launcher = run(workdir, "skip_step", False)
    launcher.launch()
    summary = runtime.health.summary()
    check(summary["skipped_steps"] >= 1, f"no skip counted: {summary}")
    check(summary["anomalies"] >= 1, f"no anomaly counted: {summary}")
    host_params = jax.device_get(grab.params)
    check(
        all(np.isfinite(leaf).all() for leaf in jax.tree.leaves(host_params)),
        "final params contain non-finite values despite skip_step",
    )

    # Leg 2: dump_and_halt — the run halts and leaves a renderable bundle.
    workdir = _workdir("blackbox_halt_")
    runtime, grab, launcher = run(workdir, "dump_and_halt", True)
    halted = False
    try:
        launcher.launch()
    except HealthAnomalyError as exc:
        halted = True
        check(exc.bundle is not None, "halt raised without a bundle path")
    check(halted, "dump_and_halt did not halt on the injected NaN")

    bundles = glob.glob(
        os.path.join(workdir, "runs", "telemetry", "blackbox", "*")
    )
    check(len(bundles) == 1, f"expected exactly one bundle, got {bundles}")
    bundle = bundles[0]
    with open(os.path.join(bundle, "blackbox.json")) as f:
        manifest = json.load(f)
    check(manifest["reason"].startswith("anomaly_step"),
          f"unexpected dump reason {manifest['reason']!r}")
    check(manifest["last_good_step"] is not None, "no last-good step recorded")
    check(len(manifest["anomalies"]) >= 1, "empty anomaly timeline")
    check(manifest["sentinel_history"], "empty sentinel history")
    check(
        os.path.exists(os.path.join(bundle, "checkpoint", "model_0",
                                    "index.json")),
        "emergency checkpoint missing from the bundle",
    )

    proc = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "blackbox", bundle],
        capture_output=True, text=True,
    )
    check(proc.returncode == 0,
          f"blackbox CLI failed: {proc.stderr[-300:]}")
    check("last good step" in proc.stdout and "anomaly timeline" in proc.stdout,
          f"blackbox CLI output incomplete:\n{proc.stdout}")

    print(
        "blackbox smoke OK: skip_step survived the NaN batch "
        f"({summary['skipped_steps']} skip(s)); dump_and_halt wrote + "
        f"rendered {os.path.basename(bundle)}"
    )


if __name__ == "__main__":
    main()
