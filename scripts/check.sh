#!/usr/bin/env bash
# One-shot CI gate: style lint (ruff) + tune table gate (checked-in
# kernel-config legality + stale structural winners) + structural
# kernel-search smoke + the `analysis all` umbrella (rocketlint +
# every audit family — shard/prec/sched/serve/calib/mem/repro/fault —
# one process, one merged findings list, budgets diffed per family) +
# seeded-bad true-positive legs (badoverlap, drifted calib, badmem,
# badrepro, badfault) + obs telemetry smoke + resilience smoke
# (supervised restart / drain) + the tier-1 test suite (command from
# ROADMAP.md).
# Exits non-zero on the first failing stage.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== ruff (style / imports) =="
if command -v ruff >/dev/null 2>&1; then
    ruff check rocket_tpu tests scripts examples bench.py
else
    echo "ruff not installed - skipping style lint (config in pyproject.toml)"
fi

echo "== tune table gate (schema + legality of checked-in kernel configs) =="
# Validates every entry in rocket_tpu/tune/configs/*.json: schema
# fields, known device kinds, bucket/shape consistency, a fresh
# legality re-verification against each kernel's TuneSpace, and the
# stale-structural-winner check — a stale or hand-edited table cannot
# ship an illegal launch config or a retired kernel variant.
JAX_PLATFORMS=cpu python -m rocket_tpu.tune --check-table

echo "== structural kernel search smoke (enumerate -> verify -> table round-trip + seeded-bad rejection) =="
# The generate-and-verify loop on CPU interpret mode (ISSUE 14): the
# structural TuneSpaces (fused_conv / block_attn) must enumerate their
# variant candidates and pass fwd+bwd parity on every one, a written
# structural winner must round-trip through the runtime lookup and
# tables_summary, a seeded wrong-but-fast fake variant must be REJECTED
# by the parity gate before timing, and a table entry pinning a retired
# variant must fail the table gate loudly.
JAX_PLATFORMS=cpu python scripts/tune_structural_smoke.py

echo "== analysis all (rocketlint + every audit family, one invocation) =="
# Replaces the per-family invocations: rocketlint over rocket_tpu/
# plus shard/prec/sched/serve/calib/mem/repro/fault, each family
# diffed against its canonical subdirectory of tests/fixtures/budgets/
# (>10% growth fails; calib uses tolerance 0.5 because its measured
# side is a live timing on a CPU container; repro fingerprints gate on
# exact equality). The merged findings land in
# runs/audit_reports/check.json — the artifact CI uploads on failure.
mkdir -p runs/audit_reports
JAX_PLATFORMS=cpu python -m rocket_tpu.analysis all rocket_tpu/ \
    --budgets tests/fixtures/budgets --calib-tolerance 0.5 \
    --json-report runs/audit_reports/check.json

echo "== overlap true-positive (seeded-bad badoverlap demo) =="
# The overlapped-collective rules must still FIND the unoverlapped
# shape they were built to kill: the seeded-bad per-param grad-psum
# convoy + sync all-gather demo must report RKT501 AND RKT502.
if JAX_PLATFORMS=cpu python -m rocket_tpu.analysis sched \
        --target badoverlap >/tmp/_badoverlap.txt 2>&1; then
    echo "badoverlap demo reported no findings - rules are broken"
    exit 1
fi
grep -q "RKT501" /tmp/_badoverlap.txt && grep -q "RKT502" /tmp/_badoverlap.txt || {
    echo "badoverlap demo missing RKT501/RKT502:"; cat /tmp/_badoverlap.txt; exit 1;
}

echo "== calibration drift true-positive (seeded-bad drifted budget) =="
# The drift gate must still FIND things: a committed budget claiming
# far tighter calibration than this machine can produce (the drifted
# fixture) must fail with RKT701.
if JAX_PLATFORMS=cpu python -m rocket_tpu.analysis calib \
        --target gpt2_sentinel \
        --budgets tests/fixtures/budgets/calib_drifted \
        --tolerance 0.5 >/tmp/_calib_drift.txt 2>&1; then
    echo "drifted calib budget passed the gate - RKT701 is broken"
    exit 1
fi
grep -q "RKT701" /tmp/_calib_drift.txt || {
    echo "drifted-budget leg missing RKT701:"; cat /tmp/_calib_drift.txt; exit 1;
}

echo "== memory true-positive (seeded-bad badmem demo) =="
# The memory rules must still FIND the failure they were built to
# kill: the undonated, remat-free long-chain demo must report exactly
# the seeded set - RKT801 (undonated state), RKT802 (remat
# ineffective) and RKT804 (over the seeded 2 MiB capacity).
if JAX_PLATFORMS=cpu python -m rocket_tpu.analysis mem \
        --target badmem --format json >/tmp/_badmem.json 2>&1; then
    echo "badmem demo reported no findings - rules are broken"
    exit 1
fi
python - <<'PY' || { echo "badmem demo rule set drifted:"; cat /tmp/_badmem.json; exit 1; }
import json
rules = {f["rule"] for f in json.load(open("/tmp/_badmem.json"))}
assert rules == {"RKT801", "RKT802", "RKT804"}, rules
PY

echo "== repro true-positive (seeded-bad badrepro demo) =="
# The determinism rules must still FIND what they were built to kill:
# the seeded reused key + unfolded loop key + non-unique float scatter
# demo must report exactly RKT901 and RKT902 — no more (rule precision)
# and no less (rule sensitivity).
if JAX_PLATFORMS=cpu python -m rocket_tpu.analysis repro \
        --target badrepro --format json >/tmp/_badrepro.json 2>&1; then
    echo "badrepro demo reported no findings - rules are broken"
    exit 1
fi
python - <<'PY' || { echo "badrepro demo rule set drifted:"; cat /tmp/_badrepro.json; exit 1; }
import json
rules = {f["rule"] for f in json.load(open("/tmp/_badrepro.json"))}
assert rules == {"RKT901", "RKT902"}, rules
PY

echo "== fault true-positive (seeded-bad badfault demo) =="
# The crash-consistency rules must still FIND what they were built to
# kill: the marker-first / unsynced-rename save order plus the
# drained-without-checkpoint transition function must report exactly
# RKT1001 + RKT1002 + RKT1003 — no more (RKT1004 precision: the demo
# keeps every terminal reachable) and no less.
if JAX_PLATFORMS=cpu python -m rocket_tpu.analysis fault \
        --target badfault --format json >/tmp/_badfault.json 2>&1; then
    echo "badfault demo reported no findings - rules are broken"
    exit 1
fi
python - <<'PY' || { echo "badfault demo rule set drifted:"; cat /tmp/_badfault.json; exit 1; }
import json
rules = {f["rule"] for f in json.load(open("/tmp/_badfault.json"))}
assert rules == {"RKT1001", "RKT1002", "RKT1003"}, rules
PY

echo "== obs smoke (telemetry + health sentinels + strict step path) =="
# Tier-1 example run with telemetry AND health sentinels on:
# telemetry.json must exist and parse, goodput categories must sum to
# wall-clock, the span file must be valid Chrome-trace JSON, the health
# gauges must be populated with zero anomalies, and the strict transfer
# guard stays green with all instrumentation active.
JAX_PLATFORMS=cpu python scripts/obs_smoke.py

echo "== blackbox smoke (injected NaN -> skip_step / forensic bundle) =="
# A poisoned batch under anomaly_action=skip_step must finish with finite
# params and a counted skip; under dump_and_halt it must halt and leave a
# complete runs/**/blackbox/ bundle the post-mortem CLI renders.
JAX_PLATFORMS=cpu python scripts/blackbox_smoke.py

echo "== resilience smoke (supervised restart after injected kill + SIGTERM drain) =="
# The supervised launcher must survive deterministic fault injection:
# one leg SIGKILLs the worker mid-run (supervisor restarts from the
# latest checkpoint, training reaches the target step, goodput_fraction
# >= 0.5 in supervisor.json), one leg SIGTERMs the supervisor (worker
# drains: emergency checkpoint + distinguished drained exit code, and a
# fresh supervised launch resumes from it).
JAX_PLATFORMS=cpu python scripts/resilience_smoke.py

echo "== serve smoke (continuous batching + paged KV + compiled-once + k-wave scan + request timelines) =="
# A 50-request synthetic workload through rocket_tpu.serve plus the
# python -m rocket_tpu.serve CLI: every request must complete, the decode
# wave / prefill chunk must each compile exactly ONCE (zero retraces
# across admissions/evictions — checked against the obs gauges in
# telemetry.json), and greedy outputs must match generate(). The scanned
# leg re-serves an identical workload with decode_waves_per_dispatch=4:
# greedy outputs bit-identical to k=1, zero retraces, and exactly one
# jax.device_get per dispatch of k waves (the tunnel amortization). The
# timeline leg (obs.reqtrace) preempts+resumes requests on a starved
# pool and gates the tail-forensics chain: one waterfall spanning both
# residencies, phases summing to wall time within 5%, the seeded SLO
# violation naming the window's exemplars, obs timeline rendering them.
JAX_PLATFORMS=cpu python scripts/serve_smoke.py

echo "== tier-1 tests =="
set -o pipefail
rm -f /tmp/_t1.log
timeout -k 10 870 env JAX_PLATFORMS=cpu python -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider -p no:xdist -p no:randomly \
    2>&1 | tee /tmp/_t1.log
rc=${PIPESTATUS[0]}
echo "DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' /tmp/_t1.log | tr -cd . | wc -c)"
exit $rc
