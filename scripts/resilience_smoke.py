#!/usr/bin/env python
"""Resilience smoke gate: supervised training must survive injected faults.

Two legs over the REAL ``python -m rocket_tpu.launch --supervise`` path
(subprocess workers, resume via ``Checkpointer(resume_from="latest")``):

* **injected kill** — ``ROCKET_TPU_FAULTS=kill:step=23`` SIGKILLs the
  worker mid-run; the supervisor must restart it, training must reach the
  target step with a finite loss, and ``supervisor.json`` must report
  ``restarts >= 1`` and ``goodput_fraction >= 0.5``;
* **SIGTERM drain** — SIGTERM to the supervisor mid-run must drain the
  worker (in-flight wave finished, emergency drain checkpoint written,
  worker exits the drained code, supervisor exits 0), and a fresh
  supervised launch must resume from that checkpoint and complete.

Exits non-zero on the first violated invariant (wired into
scripts/check.sh and CI).
"""

import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=1"
    ).strip()

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from rocket_tpu.resilience import (  # noqa: E402
    EXIT_DRAINED,
    newest_complete_step,
)

#: 320 samples / batch 32 = 10 waves per epoch x 6 epochs = 60 steps,
#: checkpointed every 5. The kill at wave 23 lands in epoch 2 with
#: checkpoints at 5..20 already durable.
TARGET_STEP = 60

_TRAIN = r"""
import os, sys, json
import jax
jax.config.update("jax_platforms", "cpu")
sys.path.insert(0, os.environ["REPO_ROOT"])

import numpy as np
import optax

import rocket_tpu as rt
from rocket_tpu import optim
from rocket_tpu.models.mlp import MLP
from rocket_tpu.runtime.context import Runtime

WORKDIR = os.environ["WORKDIR"]
runtime = Runtime(seed=0, project_dir=WORKDIR, telemetry=True)


def cross_entropy(batch):
    return optax.softmax_cross_entropy_with_integer_labels(
        batch["logits"], batch["label"]
    ).mean()


rng = np.random.default_rng(0)
data = [
    {"image": rng.normal(size=8).astype(np.float32), "label": np.int32(i % 4)}
    for i in range(320)
]

module = rt.Module(
    MLP(in_features=8, num_classes=4, hidden=(16,)),
    capsules=[rt.Loss(cross_entropy),
              rt.Optimizer(optim.adam(), learning_rate=1e-2)],
)


class Grab(rt.Capsule):
    def __init__(self):
        super().__init__(priority=10)
        self.step = None
        self.loss = None

    def launch(self, attrs=None):
        if module.state is not None:
            self.step = module.state["step"]
        if (attrs is not None and attrs.looper is not None
                and attrs.looper.state and "loss" in attrs.looper.state):
            self.loss = attrs.looper.state["loss"]


class Throttle(rt.Capsule):
    # Optional per-wave sleep (WAVE_SLEEP env) so the drain leg's SIGTERM
    # reliably lands mid-training instead of racing a sub-second run.
    def __init__(self, secs):
        super().__init__(priority=20)
        self._secs = secs

    def launch(self, attrs=None):
        if self._secs:
            import time

            time.sleep(self._secs)


grab = Grab()
tree = rt.Launcher(
    [rt.Looper(
        [rt.Dataset(data, batch_size=32, device_cache=False),
         module, grab,
         Throttle(float(os.environ.get("WAVE_SLEEP", "0") or 0)),
         rt.Checkpointer(output_dir=os.path.join(WORKDIR, "ckpts"),
                         save_every=5, resume_from="latest")],
        tag="train", progress=False)],
    num_epochs=6, statefull=True, runtime=runtime,
)
tree.launch()
final = {"step": int(np.asarray(jax.device_get(grab.step))),
         "loss": float(np.asarray(jax.device_get(grab.loss)))}
with open(os.path.join(WORKDIR, "done.json"), "w") as f:
    json.dump(final, f)
print("TRAIN_DONE", json.dumps(final), flush=True)
"""


def check(condition, message):
    if not condition:
        print(f"resilience smoke FAILED: {message}", file=sys.stderr)
        sys.exit(1)


def _workdir(prefix):
    # Under the repo's (gitignored) runs/ — NOT the system tmpdir — so a
    # failing CI run's supervisor.json + telemetry land inside the
    # workspace where the runs/** artifact-upload step can find them.
    # A SUCCESSFUL leg removes its workdir (check() exits before the
    # cleanup on any failure), so repeated local runs don't accumulate
    # checkpoint trees.
    repo_runs = os.path.join(REPO, "runs")
    os.makedirs(repo_runs, exist_ok=True)
    return tempfile.mkdtemp(prefix=prefix, dir=repo_runs)


def _setup(workdir, extra_env=None):
    script = os.path.join(workdir, "train.py")
    with open(script, "w") as f:
        f.write(_TRAIN)
    env = dict(os.environ)
    env.update(REPO_ROOT=REPO, WORKDIR=workdir, JAX_PLATFORMS="cpu")
    env.pop("ROCKET_TPU_FAULTS", None)
    env.update(extra_env or {})
    state_dir = os.path.join(workdir, "runs", "telemetry")
    cmd = [
        sys.executable, "-m", "rocket_tpu.launch", "--supervise", "-n", "1",
        "--ckpt-dir", os.path.join(workdir, "ckpts"),
        "--state-dir", state_dir,
        "--backoff", "0.1", "--progress-grace", "0.5",
        "--term-grace", "10", "--drain-grace", "60",
        script,
    ]
    return cmd, env, state_dir


def _read_supervisor(state_dir):
    with open(os.path.join(state_dir, "supervisor.json")) as f:
        return json.load(f)


def leg_injected_kill():
    workdir = _workdir("resilience_kill_")
    cmd, env, state_dir = _setup(
        workdir, {"ROCKET_TPU_FAULTS": "kill:step=23"}
    )
    proc = subprocess.run(cmd, env=env, cwd=REPO, capture_output=True,
                          text=True, timeout=600)
    check(proc.returncode == 0,
          f"supervised run exited {proc.returncode}:\n{proc.stdout[-2000:]}"
          f"\n{proc.stderr[-1000:]}")

    done = json.load(open(os.path.join(workdir, "done.json")))
    check(done["step"] == TARGET_STEP,
          f"training did not reach step {TARGET_STEP}: {done}")
    check(done["loss"] == done["loss"] and abs(done["loss"]) < 1e9,
          f"non-finite final loss: {done}")

    sup = _read_supervisor(state_dir)
    check(sup["outcome"] == "completed", f"outcome {sup['outcome']!r}")
    check(sup["restarts"] >= 1, f"no restart recorded: {sup['restarts']}")
    check(len(sup["generations"]) >= 2, "fewer than 2 generations")
    check(sup["generations"][0]["outcome"] == "crashed",
          f"gen 0 outcome {sup['generations'][0]['outcome']!r} "
          "(the injected SIGKILL)")
    check(sup["goodput_fraction"] >= 0.5,
          f"goodput_fraction {sup['goodput_fraction']} < 0.5 under one "
          "injected kill")

    # The obs report CLI folds the supervisor section into the telemetry
    # report (supervisor.json sits next to telemetry.json).
    report = subprocess.run(
        [sys.executable, "-m", "rocket_tpu.obs", "report",
         os.path.join(state_dir, "telemetry.json")],
        capture_output=True, text=True, cwd=REPO,
    )
    check(report.returncode == 0, f"obs report failed: {report.stderr[-400:]}")
    check("supervisor: outcome=completed" in report.stdout,
          f"obs report missing supervisor section:\n{report.stdout}")
    shutil.rmtree(workdir, ignore_errors=True)
    return sup


def leg_sigterm_drain():
    workdir = _workdir("resilience_drain_")
    # ~80ms per wave => ~5s of training: the SIGTERM below cannot race a
    # sub-second run to completion.
    cmd, env, state_dir = _setup(workdir, {"WAVE_SLEEP": "0.08"})
    proc = subprocess.Popen(cmd, env=env, cwd=REPO,
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    # Wait for durable progress, then deliver the preemption notice.
    deadline = time.time() + 300
    ckpt_dir = os.path.join(workdir, "ckpts")
    while time.time() < deadline:
        step = newest_complete_step(ckpt_dir)
        if step is not None and step >= 5:
            break
        if proc.poll() is not None:
            out = proc.communicate()[0]
            check(False, f"supervised run died before progress:\n{out[-2000:]}")
        time.sleep(0.2)
    else:
        proc.kill()
        check(False, "no checkpoint progress within 300s")
    proc.send_signal(signal.SIGTERM)
    try:
        out = proc.communicate(timeout=120)[0]
    except subprocess.TimeoutExpired:
        proc.kill()
        check(False, "supervisor did not exit within 120s of SIGTERM")
    check(proc.returncode == 0,
          f"drain exited {proc.returncode} (expected clean 0):\n{out[-2000:]}")

    sup = _read_supervisor(state_dir)
    check(sup["outcome"] == "drained", f"outcome {sup['outcome']!r}")
    check(sup["drain_events"] >= 1, "no drain event recorded")
    last = sup["generations"][-1]
    check(last["outcome"] == "drained", f"generation outcome {last!r}")
    check(EXIT_DRAINED in last["exit_codes"],
          f"worker did not exit the drained code: {last['exit_codes']}")

    # The drain left an emergency checkpoint in the numbered layout.
    drained_step = newest_complete_step(ckpt_dir)
    check(drained_step is not None, "no complete checkpoint after drain")
    marker = os.path.join(ckpt_dir, str(drained_step), "drain.json")
    check(os.path.exists(marker),
          f"drain checkpoint marker missing at {marker}")

    # A fresh supervised launch resumes from the drained checkpoint and
    # completes to the target step.
    cmd2, env2, state_dir2 = _setup(workdir)
    proc2 = subprocess.run(cmd2, env=env2, cwd=REPO, capture_output=True,
                           text=True, timeout=600)
    check(proc2.returncode == 0,
          f"resume-after-drain exited {proc2.returncode}:"
          f"\n{proc2.stdout[-2000:]}")
    done = json.load(open(os.path.join(workdir, "done.json")))
    check(done["step"] == TARGET_STEP,
          f"resume-after-drain did not reach step {TARGET_STEP}: {done}")
    shutil.rmtree(workdir, ignore_errors=True)
    return sup, drained_step


def main(argv=None) -> None:
    # --leg/--json-out exist for bench.py's `resilience_summary`, which
    # runs the kill leg as a subprocess probe and reads the record back.
    import argparse

    parser = argparse.ArgumentParser()
    parser.add_argument("--leg", choices=["all", "kill", "drain"],
                        default="all")
    parser.add_argument("--json-out", default=None,
                        help="write the kill leg's headline record here")
    args = parser.parse_args(argv)

    sup_kill = None
    if args.leg in ("all", "kill"):
        sup_kill = leg_injected_kill()
        if args.json_out:
            record = {
                "outcome": sup_kill["outcome"],
                "restarts": sup_kill["restarts"],
                "generations": len(sup_kill["generations"]),
                "goodput_fraction": sup_kill["goodput_fraction"],
                "total_wall_s": sup_kill["total_wall_s"],
                "target_step": TARGET_STEP,
                "fault": "kill:step=23",
            }
            with open(args.json_out, "w") as f:
                json.dump(record, f)
    if args.leg in ("all", "drain"):
        sup_drain, drained_step = leg_sigterm_drain()

    if args.leg == "all":
        print(
            "resilience smoke OK: injected kill survived with "
            f"{sup_kill['restarts']} restart(s), goodput_fraction="
            f"{sup_kill['goodput_fraction']}; SIGTERM drained cleanly at "
            f"checkpoint step {drained_step} and resumed to step "
            f"{TARGET_STEP}"
        )
    else:
        print(f"resilience smoke OK ({args.leg} leg)")


if __name__ == "__main__":
    main()
